"""Llama serving engine: continuous batching over a paged KV cache.

Reference parity: the reference's serving stack (PaddleNLP predictor with
block_multihead_attention + BlockManager) admits/evicts requests mid-
flight, storing KV in fixed-size blocks. TPU-native redesign:

  * one jitted `prefill` (dense causal flash attention, bucketed prompt
    lengths to bound recompiles) that also returns per-layer K/V to be
    scattered into the page pool;
  * one jitted `decode_step` for the WHOLE active batch: lax.scan over
    the stacked layer params, paged-attention pallas kernel per layer,
    functional scatter of the new token's K/V into the pool (inactive
    slots write to a reserved trash page);
  * host-side PagedKVCache free-list bookkeeping between steps — slots
    join/leave the batch without recompilation (page_table/lengths are
    plain inputs).

All shapes static: batch = max_seqs always; inactive slots are masked.
"""
from __future__ import annotations

import functools
import math
import os
import time

import numpy as np
import jax
import jax.numpy as jnp
from .._core.compat import shard_map

from .. import _tuning_defaults as _tuning
from ..kernels.ragged_paged_attention import ragged_paged_attention
from ..observability import compile_telemetry as _compile
from ..observability.device_telemetry import device_generation
from ..observability import flight_recorder as _flight
from ..observability.compile_telemetry import track_jit
from ..profiler import record_span
# host-side page bookkeeping only (numpy/stdlib — serving.kvcache,
# serving.kvtier and serving.faults never import model/engine code, so
# this direction stays cycle-free)
from ..serving.faults import FaultPlan
from ..serving.handoff import KVHandoff
from ..serving.kvcache import PagePool, PrefixCache
from ..serving.kvtier import HostTier, _dequantize_host, _quantize_host
from ..ops.rope import rope_cos_sin, apply_rotary_emb
from ..ops.flash_attention import flash_attention_bhsd
from ..ops.paged_attention import (paged_attention, paged_verify_attention,
                                   quantize_kv)
from ..ops.varlen_attention import (flash_attention_varlen,
                                    seg_ids_from_cu_seqlens)
from .generation import filtered_probs_np
from .llama import LlamaConfig

_compile_cache_wired = False


def _wire_compile_cache():
    """Enable jax's persistent compilation cache once per process when
    PT_COMPILE_CACHE=<dir> is set (docs/reliability.md § restart
    runbook): a warm restart or rolling drain replays its compiles from
    disk instead of re-lowering every serving trace. Thresholds are
    zeroed so even small serving programs persist. Best-effort — an
    old jax or a read-only dir must never block engine construction."""
    global _compile_cache_wired
    if _compile_cache_wired:
        return
    _compile_cache_wired = True
    cache_dir = os.environ.get("PT_COMPILE_CACHE", "")
    if not cache_dir:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
    except Exception:
        return
    _compile.REGISTRY.note_persistent_cache(cache_dir)


def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    return (xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
            * w.astype(jnp.float32)).astype(x.dtype)


def _scatter_kv(kp, vp, ksp, vsp, li, page_ids, off, kt, vt, quant):
    """Write kt/vt (KVH, *idx, D) into layer li of the K/V pools at
    (page_ids, off) — *idx is page_ids/off's shape — quantizing on write
    when the pool is int8 (per-token scales ride in ksp/vsp). Single
    source for decode_step's one-token and verify_step's G-token
    scatters so the int8 path can never drift between them. Returns
    (kp, vp, ksp, vsp, kl, vl, ksl, vsl): the updated stacks plus this
    layer's views for the attention read."""
    kl = jax.lax.dynamic_index_in_dim(kp, li, 0, keepdims=False)
    vl = jax.lax.dynamic_index_in_dim(vp, li, 0, keepdims=False)
    ksl = vsl = None
    if quant:
        kt, kts = quantize_kv(kt)
        vt, vts = quantize_kv(vt)
        ksl = jax.lax.dynamic_index_in_dim(ksp, li, 0, keepdims=False)
        vsl = jax.lax.dynamic_index_in_dim(vsp, li, 0, keepdims=False)
        ksl = ksl.at[:, page_ids, off].set(kts)
        vsl = vsl.at[:, page_ids, off].set(vts)
        ksp = jax.lax.dynamic_update_index_in_dim(ksp, ksl, li, 0)
        vsp = jax.lax.dynamic_update_index_in_dim(vsp, vsl, li, 0)
    kl = kl.at[:, page_ids, off].set(kt.astype(kl.dtype))
    vl = vl.at[:, page_ids, off].set(vt.astype(vl.dtype))
    kp = jax.lax.dynamic_update_index_in_dim(kp, kl, li, 0)
    vp = jax.lax.dynamic_update_index_in_dim(vp, vl, li, 0)
    return kp, vp, ksp, vsp, kl, vl, ksl, vsl


def _sample_record(logits, lengths, active, sample):
    """Device-side sampling + stop-condition evaluation, fused into the
    step program (ROADMAP item 4 / MPK direction: the host reads a few
    ints per slot instead of `[vocab]` rows, and the pipelined pump can
    consume them one step behind).

    Every sampling parameter is a TRACED per-slot array — temperature /
    top_k / top_p changing between requests can never retrace:
      temp (B,) f32      0 = greedy (device argmax);
      top_k (B,) i32     0 = off, clamped to vocab;
      top_p (B,) f32     1.0 = off (include-crossing-token convention,
                         same as generation._sample_logits);
      key (B, 2) u32     the request's base PRNG key; the step key is
                         fold_in(key, lengths) — a pure function of
                         (seed, position), so a preempted/restored
                         request continues the identical trajectory and
                         the sync and pipelined pumps are token-equal;
      eos (B,) i32       -1 = no eos;
      remaining (B,) i32 tokens of budget left including this one.

    Returns (next_token (B,) i32, done (B,) bool, logprob (B,) f32) —
    logprob is log p(token | context) under the RAW model distribution
    (the `logprobs=True` convention), computed here so even logprobs
    requests transfer one float, not a vocab row.
    """
    tok, lp = _filter_draw(logits.astype(jnp.float32), sample["temp"],
                           sample["top_k"], sample["top_p"],
                           sample["key"], lengths)
    done = active & ((sample["remaining"] <= 1) |
                     ((sample["eos"] >= 0) & (tok == sample["eos"])))
    return tok, done, lp


def _filter_draw(lg, temp, top_k, top_p, key, fold):
    """Filtered categorical draw shared by the decode record and the
    verify grid: lg (N, V) f32 logits; temp/top_k/top_p/fold (N,)
    traced; key (N, 2) u32. Returns (token (N,) i32, raw-model logprob
    at that token (N,) f32). temp == 0 rows take the argmax.

    top_k/top_p are TRACED (a lax.top_k would need static k), so the
    filter is ONE descending value sort + threshold arithmetic — no
    argsort/unsort round trip, which matters because this graph is
    inlined into every decode_step/verify_step compile. top_p keeps
    the include-crossing-token convention measured on the top-k-
    renormalized distribution (same as the host sampler's
    filter-then-renormalize order): with Z = cumulative prob mass of
    the top-k set, `cum - prob <= p * Z` over UNfiltered probs is
    exactly `cum_f - prob_f <= p` over the filtered ones."""
    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    sampled_on = temp > 0.0
    lt = _filtered_logits(lg, temp, top_k, top_p)
    step_key = jax.vmap(jax.random.fold_in)(key, fold)
    drawn = jax.vmap(jax.random.categorical)(step_key, lt) \
        .astype(jnp.int32)
    tok = jnp.where(sampled_on, drawn, greedy)
    lp = jnp.take_along_axis(jax.nn.log_softmax(lg, axis=-1),
                             tok[:, None], axis=-1)[:, 0]
    return tok, lp


def _filtered_logits(lg, temp, top_k, top_p):
    """The temperature/top_k/top_p filter HALF of `_filter_draw`:
    lg (N, V) f32 → filtered temperature-scaled logits (kept tokens
    untouched, dropped ones -1e30). ONE definition shared by the
    device draw and the spec-decode candidate-probability path, so the
    distribution a rejection sampler accepts against is exactly the
    distribution the device sampler draws from."""
    V = lg.shape[-1]
    sampled_on = temp > 0.0
    # greedy rows run the sampler arithmetic too (masked out by the
    # caller's final where): a per-row branch would be value-dependent
    # control flow. Guard the divide so temp=0 rows cannot overflow.
    lt = lg / jnp.where(sampled_on, jnp.maximum(temp, 1e-6), 1.0)[:, None]
    k = jnp.where(top_k > 0, jnp.minimum(top_k, V), V)
    sv = -jnp.sort(-lt, axis=-1)                     # descending values
    probs = jax.nn.softmax(sv, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    z = jnp.take_along_axis(cum, (k - 1)[:, None], axis=-1)
    keep = (jnp.arange(V)[None, :] < k[:, None]) & \
        (cum - probs <= top_p[:, None] * z)
    nkeep = jnp.maximum(keep.sum(-1), 1)             # crossing token stays
    thresh = jnp.take_along_axis(sv, (nkeep - 1)[:, None], axis=-1)
    return jnp.where(lt < thresh, -1e30, lt)


@jax.jit
def _spec_dist_rows(lg, temp, top_k, top_p):
    """Filtered sampling DISTRIBUTION rows for the spec-decode
    rejection sampler: lg (N, V) f32 raw logits → (N, V) f32 softmax
    over `_filtered_logits`. Fixed caller shapes (one row at a time on
    the lazy rejection path) keep this at one compile."""
    return jax.nn.softmax(
        _filtered_logits(lg.astype(jnp.float32), temp, top_k, top_p),
        axis=-1)


def _sample_grid(logits, lengths, sample):
    """Verify-chunk twin of `_sample_record`: logits (B, G, V), one
    draw per chunk position. The emission following chunk token g sits
    at cache position lengths+g+1 pre-advanced — exactly the fold the
    plain decode path uses for that emission index, so an un-drafted
    sampled request in a verify chunk draws the IDENTICAL token the
    plain engine would (cross-mode seeded parity). Returns
    (token (B, G) i32, logprob (B, G) f32)."""
    B, G, V = logits.shape
    lg = logits.astype(jnp.float32).reshape(B * G, V)
    pos = (lengths[:, None] + jnp.arange(G)[None, :] + 1).reshape(-1)

    def rep(a):
        return jnp.repeat(a, G, axis=0)
    tok, lp = _filter_draw(lg, rep(sample["temp"]), rep(sample["top_k"]),
                           rep(sample["top_p"]), rep(sample["key"]), pos)
    return tok.reshape(B, G), lp.reshape(B, G)


def _sample_flat(logits, tok_slot, tok_pos, row_on, sample):
    """Flat-row twin of `_sample_record`/`_sample_grid` for the unified
    ragged step: logits (T, V), one draw per buffer row. Per-slot
    sampling params gather through `tok_slot`; the PRNG fold is
    `tok_pos + 1` — exactly the (seed, position) key BOTH bucketed
    paths use (decode folds on pre-advanced lengths = fed-token
    position + 1; the verify grid folds on lengths + g + 1), so the
    ragged engine draws the identical token stream for identical
    logits, across sync and pipelined pumps. Spec engines evaluate
    stop conditions on host (their sample pytree carries no
    eos/remaining) — their rows return done=False. Returns
    (next_token (T,) i32, done (T,) bool, logprob (T,) f32)."""

    def g(a):
        return a[tok_slot]
    tok, lp = _filter_draw(logits.astype(jnp.float32), g(sample["temp"]),
                           g(sample["top_k"]), g(sample["top_p"]),
                           g(sample["key"]), tok_pos + 1)
    if "remaining" in sample:
        done = row_on & ((g(sample["remaining"]) <= 1) |
                         ((g(sample["eos"]) >= 0) & (tok == g(sample["eos"]))))
    else:
        done = jnp.zeros_like(row_on)
    return tok, done, lp


def _cand_probs(logits, tok_slot, sample, cand):
    """Per-row filtered-distribution probability of a CANDIDATE token
    (the spec-decode draft that follows the row): logits (R, V), cand
    (R,) i32 → (R,) f32. Shares `_filtered_logits` with the device
    draw, so the probability the rejection sampler accepts a draft
    with is computed under exactly the distribution the device would
    sample from — and the host fetches R floats instead of R vocab
    rows (XLA CSEs the filter against `_sample_flat`'s)."""
    def g(a):
        return a[tok_slot]
    lt = _filtered_logits(logits.astype(jnp.float32), g(sample["temp"]),
                          g(sample["top_k"]), g(sample["top_p"]))
    dist = jax.nn.softmax(lt, axis=-1)
    return jnp.take_along_axis(dist, cand[:, None], axis=-1)[:, 0]


def _attn_tp(fn, mesh, quant):
    """shard_map wrapper for the paged attention kernels under tensor
    parallelism: attention is embarrassingly parallel over heads, so
    each tp rank runs the unmodified kernel on its local Q heads
    (P(None, 'tp')) against its local KV heads (P('tp')) — GQA group
    ratios survive because the engine requires nh % tp == kvh % tp == 0.
    Everything around the kernel (matmuls, scatters, MLP) stays under
    GSPMD; only the pallas call needs the manual region (reference: the
    block_multi_head_attention kernel under fleet TP,
    paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu +
    distributed/fleet/meta_parallel/parallel_layers/mp_layers.py)."""
    from jax.sharding import PartitionSpec as P
    qs, kvs, rep = P(None, "tp"), P("tp"), P(None)
    in_specs = (qs, kvs, kvs, rep, rep) + ((kvs, kvs) if quant else ())
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=qs,
                     check_vma=False)


# ---------------------------------------------------------------------------
# jitted compute
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("config", "use_pallas"))
def prefill(params, input_ids, length, config: LlamaConfig, use_pallas=False):
    """input_ids: (1, S_padded); length: () actual prompt length.
    Returns (next_logits (V,), k_all, v_all: (L, KVH, S_padded, D))."""
    c = config
    nh, nkv = c.num_attention_heads, c.num_key_value_heads
    hd = c.hidden_size // nh
    b, s = input_ids.shape
    cos, sin = rope_cos_sin(s, hd, base=c.rope_theta, dtype=jnp.float32)
    h = jnp.take(params["embed"], input_ids, axis=0)

    def layer(h, lp):
        x = _rms(h, lp["ln1"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(b, s, nh, hd).swapaxes(1, 2)
        k = (x @ lp["wk"]).reshape(b, s, nkv, hd).swapaxes(1, 2)
        v = (x @ lp["wv"]).reshape(b, s, nkv, hd).swapaxes(1, 2)
        q, k = apply_rotary_emb(q, k, cos[None, None], sin[None, None])
        rep = nh // nkv
        kr = jnp.repeat(k, rep, axis=1) if rep > 1 else k
        vr = jnp.repeat(v, rep, axis=1) if rep > 1 else v
        o = flash_attention_bhsd(q, kr, vr, causal=True,
                                 use_pallas=use_pallas)
        h = h + o.swapaxes(1, 2).reshape(b, s, -1) @ lp["wo"]
        x = _rms(h, lp["ln2"], c.rms_norm_eps)
        mlp = (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
        return h + mlp, (k[0], v[0])

    h, kv = jax.lax.scan(layer, h, params["layers"])
    h = _rms(h, params["final_norm"], c.rms_norm_eps)
    logits = h[0, length - 1] @ params["lm_head"]
    return logits, kv[0], kv[1]


@functools.partial(jax.jit, static_argnames=("config", "use_pallas",
                                             "interpret"))
def prefill_varlen(params, input_ids, cu_seqlens, config: LlamaConfig,
                   use_pallas=False, interpret=False):
    """Ragged-batch prefill in ONE call (reference parity:
    flash_attn_unpadded serving prefill).

    input_ids: (T_pad,) all admitted prompts packed back to back;
    cu_seqlens: (B+1,) prefix sums (fixed length → batch-size changes
    don't recompile; unused tail entries repeat the last offset).
    Returns (per-seq next-token logits (B, V),
             k_all, v_all: (L, KVH, T_pad, D))."""
    c = config
    nh, nkv = c.num_attention_heads, c.num_key_value_heads
    hd = c.hidden_size // nh
    t = input_ids.shape[0]
    seg = seg_ids_from_cu_seqlens(cu_seqlens, t)
    # in-segment position for RoPE (0 for padding; masked away anyway)
    starts = jnp.concatenate([cu_seqlens[:1] * 0, cu_seqlens])[seg + 1]
    pos = jnp.maximum(jnp.arange(t, dtype=jnp.int32) - starts, 0)
    cos, sin = rope_cos_sin(None, hd, base=c.rope_theta,
                            position_ids=pos)          # (T, hd)
    h = jnp.take(params["embed"], input_ids, axis=0)   # (T, H)

    def layer(h, lp):
        x = _rms(h, lp["ln1"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(t, nh, hd)
        k = (x @ lp["wk"]).reshape(t, nkv, hd)
        v = (x @ lp["wv"]).reshape(t, nkv, hd)
        q, k = apply_rotary_emb(q, k, cos[:, None], sin[:, None])
        o = flash_attention_varlen(q, k, v, seg, seg, causal=True,
                                   use_pallas=use_pallas,
                                   interpret=interpret,
                                   same_offsets=True)   # (T, nh, hd)
        h = h + o.reshape(t, -1) @ lp["wo"]
        x = _rms(h, lp["ln2"], c.rms_norm_eps)
        mlp = (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
        return h + mlp, (k, v)

    h, kv = jax.lax.scan(layer, h, params["layers"])
    h = _rms(h, params["final_norm"], c.rms_norm_eps)
    last = jnp.maximum(cu_seqlens[1:] - 1, 0)          # (B,)
    logits = h[last] @ params["lm_head"]               # (B, V)
    # (L, T, KVH, D) → (L, KVH, T, D) to match the pool scatter layout
    k_all = jnp.swapaxes(kv[0], 1, 2)
    v_all = jnp.swapaxes(kv[1], 1, 2)
    return logits, k_all, v_all


@functools.partial(jax.jit,
                   static_argnames=("config", "use_pallas", "page_size",
                                    "interpret", "mesh"))
def decode_step(params, k_pool, v_pool, page_table, lengths, tokens,
                active, config: LlamaConfig, page_size, use_pallas=False,
                interpret=False, k_scale=None, v_scale=None, mesh=None,
                sample=None, carry_tok=None, carry_mask=None):
    """One token for every slot.

    k_pool/v_pool: (L, KVH, P, page, D); tokens: (B,) current input token;
    lengths: (B,) length INCLUDING the current token; active: (B,) bool.
    With an int8 cache, k_scale/v_scale (L, KVH, P, page, 1) fp32 ride
    along: the new token's K/V is quantized in-graph and the attention
    kernel dequantizes on read.
    Returns (k_pool, v_pool, k_scale, v_scale, logits (B, V)).

    `sample` (traced pytree, see `_sample_record`) moves sampling and
    stop-condition evaluation INTO this program: the return gains a
    compact (next_token, done, logprob) record and the host never
    needs a logits row. `carry_tok`/`carry_mask` ((B,) i32 / bool,
    both traced) let the pipelined pump feed slot s the PREVIOUS
    step's device-resident next_token (mask true) instead of a host
    value — the autoregressive dependency stays on device, so step
    N+1 launches before the host has read step N.
    """
    c = config
    if carry_tok is not None:
        tokens = jnp.where(carry_mask, carry_tok, tokens)
    nh, nkv = c.num_attention_heads, c.num_key_value_heads
    hd = c.hidden_size // nh
    B = tokens.shape[0]
    P = k_pool.shape[2]
    quant = k_scale is not None

    pos = jnp.maximum(lengths - 1, 0)                       # (B,)
    cos, sin = rope_cos_sin(None, hd, base=c.rope_theta,
                            position_ids=pos[:, None])      # (B, 1, hd)
    h = jnp.take(params["embed"], tokens[:, None], axis=0)  # (B, 1, H)

    page_ids = page_table[jnp.arange(B), pos // page_size]
    page_ids = jnp.where(active, page_ids, P - 1)           # trash page
    off = pos % page_size

    def layer(carry, xs):
        h, kp, vp, ksp, vsp = carry
        lp, li = xs
        x = _rms(h, lp["ln1"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(B, 1, nh, hd).swapaxes(1, 2)
        k = (x @ lp["wk"]).reshape(B, 1, nkv, hd).swapaxes(1, 2)
        v = (x @ lp["wv"]).reshape(B, 1, nkv, hd).swapaxes(1, 2)
        q, k = apply_rotary_emb(q, k, cos[:, None], sin[:, None])
        # write this token's K/V: (B, KVH, D) → pool[li][:, page_ids, off]
        kt = k[:, :, 0].swapaxes(0, 1)                      # (KVH, B, D)
        vt = v[:, :, 0].swapaxes(0, 1)
        kp, vp, ksp, vsp, kl, vl, ksl, vsl = _scatter_kv(
            kp, vp, ksp, vsp, li, page_ids, off, kt, vt, quant)
        if mesh is not None:
            # scales arrive as explicit defaulted params (not a *sc
            # truthiness branch): the arity is fixed by `quant`, which
            # is static, so the trace has no value-dependent control flow
            def _attn(q_, kl_, vl_, pt_, ln_, ks_=None, vs_=None):
                return paged_attention(
                    q_, kl_, vl_, pt_, ln_, use_pallas=use_pallas,
                    interpret=interpret, k_scale=ks_, v_scale=vs_)
            args = (q[:, :, 0], kl, vl, page_table, lengths) \
                + ((ksl, vsl) if quant else ())
            o = _attn_tp(_attn, mesh, quant)(*args)         # (B, QH, D)
        else:
            o = paged_attention(q[:, :, 0], kl, vl, page_table, lengths,
                                use_pallas=use_pallas, interpret=interpret,
                                k_scale=ksl, v_scale=vsl)   # (B, QH, D)
        h = h + o.reshape(B, 1, -1).astype(h.dtype) @ lp["wo"]
        x = _rms(h, lp["ln2"], c.rms_norm_eps)
        mlp = (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
        return (h + mlp, kp, vp, ksp, vsp), None

    L = k_pool.shape[0]
    (h, k_pool, v_pool, k_scale, v_scale), _ = jax.lax.scan(
        layer, (h, k_pool, v_pool, k_scale, v_scale),
        (params["layers"], jnp.arange(L)))
    h = _rms(h, params["final_norm"], c.rms_norm_eps)
    logits = h[:, 0] @ params["lm_head"]
    if sample is None:
        return k_pool, v_pool, k_scale, v_scale, logits
    rec = _sample_record(logits, lengths, active, sample)
    return k_pool, v_pool, k_scale, v_scale, logits, rec


@functools.partial(jax.jit,
                   static_argnames=("config", "page_size", "use_pallas",
                                    "interpret", "mesh"))
def verify_step(params, k_pool, v_pool, page_table, lengths, tokens,
                n_tok, active, config: LlamaConfig, page_size,
                use_pallas=False, interpret=False,
                k_scale=None, v_scale=None, mesh=None, sample=None,
                need_rows=None, cand_tok=None):
    """Speculative-decoding verify: G chunk tokens per slot in ONE
    forward — every matmul runs at (B, G, ...) so one weight read
    covers G tokens, which is where the speculative speedup comes from
    (reference parity: PaddleNLP speculative decoding / "inference with
    reference" draft-verify flow).

    tokens: (B, G) = [pending next_token, draft_1 .. draft_{G-1}],
    right-padded per slot; n_tok: (B,) real chunk length (1..G) — padded
    positions write their K/V to the trash page (their page-table slots
    may not exist, and a default 0 entry would corrupt another slot's
    page 0). lengths: (B,) cache length BEFORE this chunk (chunk token g
    lands at position lengths+g — NB different convention from
    decode_step, which takes lengths pre-advanced); active: (B,) bool.

    Real chunk tokens' K/V are written to the pool; entries past the
    host-side accepted prefix simply sit beyond the slot's length,
    masked from every future read and overwritten when those positions
    are legitimately reached. Returns (k_pool, v_pool, k_scale, v_scale,
    logits (B, G, V)) — logits[:, g] follows chunk token g.

    Attention runs the multi-query paged kernel
    (ops/paged_attention.paged_verify_attention): pages stream
    HBM→VMEM via scalar-prefetch index maps with a per-row causal
    limit — no contiguous gather of the cache. Off-TPU the XLA
    reference (gather + masked dense block) runs instead.
    """
    c = config
    nh, nkv = c.num_attention_heads, c.num_key_value_heads
    hd = c.hidden_size // nh
    B, G = tokens.shape
    Pn = k_pool.shape[2]
    quant = k_scale is not None

    pos = lengths[:, None] + jnp.arange(G)[None, :]          # (B, G)
    cos, sin = rope_cos_sin(None, hd, base=c.rope_theta,
                            position_ids=pos)                # (B, G, hd)
    h = jnp.take(params["embed"], tokens, axis=0)            # (B, G, H)

    page_ids = page_table[jnp.arange(B)[:, None], pos // page_size]
    real = active[:, None] & (jnp.arange(G)[None, :] < n_tok[:, None])
    page_ids = jnp.where(real, page_ids, Pn - 1)             # trash page
    off = pos % page_size                                    # (B, G)

    def layer(carry, xs):
        h, kp, vp, ksp, vsp = carry
        lp, li = xs
        x = _rms(h, lp["ln1"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(B, G, nh, hd).swapaxes(1, 2)
        k = (x @ lp["wk"]).reshape(B, G, nkv, hd).swapaxes(1, 2)
        v = (x @ lp["wv"]).reshape(B, G, nkv, hd).swapaxes(1, 2)
        q, k = apply_rotary_emb(q, k, cos[:, None], sin[:, None])
        kt = k.swapaxes(0, 1)                                # (KVH, B, G, D)
        vt = v.swapaxes(0, 1)
        kp, vp, ksp, vsp, kl, vl, ksl, vsl = _scatter_kv(
            kp, vp, ksp, vsp, li, page_ids, off, kt, vt, quant)
        # q: (B, QH, G, D); per-row causal limit base+g inside the op
        if mesh is not None:
            # see prefill `_attn`: fixed arity instead of *sc truthiness
            def _attn(q_, kl_, vl_, pt_, ln_, ks_=None, vs_=None):
                return paged_verify_attention(
                    q_, kl_, vl_, pt_, ln_, use_pallas=use_pallas,
                    interpret=interpret, k_scale=ks_, v_scale=vs_)
            args = (q, kl, vl, page_table, lengths) \
                + ((ksl, vsl) if quant else ())
            o = _attn_tp(_attn, mesh, quant)(*args)
        else:
            o = paged_verify_attention(q, kl, vl, page_table, lengths,
                                       use_pallas=use_pallas,
                                       interpret=interpret,
                                       k_scale=ksl, v_scale=vsl)
        o = o.swapaxes(1, 2).reshape(B, G, nh * hd)
        h = h + o.astype(h.dtype) @ lp["wo"]
        x = _rms(h, lp["ln2"], c.rms_norm_eps)
        mlp = (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
        return (h + mlp, kp, vp, ksp, vsp), None

    L = k_pool.shape[0]
    (h, k_pool, v_pool, k_scale, v_scale), _ = jax.lax.scan(
        layer, (h, k_pool, v_pool, k_scale, v_scale),
        (params["layers"], jnp.arange(L)))
    h = _rms(h, params["final_norm"], c.rms_norm_eps)
    if need_rows is not None:
        # lean epilogue (suffix-prefill path): gather the needed flat
        # (B*G)-space rows before the unembed matmul — a bucket-G
        # chunk pays len(need_rows) rows of lm_head FLOPs, not B*G.
        # Callers pass sample=None here (the seed token is picked
        # host-side at finish, the PR 8 convention).
        hf = h.reshape(B * G, -1)[jnp.maximum(need_rows, 0)]
        logits = hf @ params["lm_head"]              # (M, V)
        return k_pool, v_pool, k_scale, v_scale, logits
    logits = h @ params["lm_head"]
    if sample is None:
        return k_pool, v_pool, k_scale, v_scale, logits
    # device-side verify record (`sample` = the same traced pytree as
    # decode_step's): per-position continuation tokens — argmax for
    # greedy slots, the position-keyed categorical draw for sampled
    # ones — and their raw-model logprobs. The host acceptance loop
    # consumes (B, G) ints/floats, never a vocab row; spec_sample's
    # rejection sampler rides `cand_tok` candidate probabilities
    # (computed under the device filter) and pulls a distribution row
    # only on divergence.
    rec = _sample_grid(logits, lengths, sample)
    if cand_tok is not None:
        slot_of = jnp.repeat(jnp.arange(B, dtype=jnp.int32), G)
        cand_p = _cand_probs(logits.reshape(B * G, -1), slot_of,
                             sample, cand_tok.reshape(-1))
        rec = rec + (cand_p.reshape(B, G),)
    return k_pool, v_pool, k_scale, v_scale, logits, rec


@functools.partial(jax.jit,
                   static_argnames=("config", "page_size", "use_pallas",
                                    "interpret", "block_q",
                                    "block_pages"))
def unified_step(params, k_pool, v_pool, page_table, tokens, tok_slot,
                 tok_pos, config: LlamaConfig, page_size,
                 use_pallas=False, interpret=False, k_scale=None,
                 v_scale=None, sample=None, carry_tok=None,
                 carry_gather=None, carry_mask=None, need_rows=None,
                 cand_tok=None, block_q=None, block_pages=None,
                 tok_buf=None, buf_write=None):
    """ONE device program for an arbitrary prefill/decode mix (ROADMAP
    item 1; "Ragged Paged Attention" + the MPK fewer-bigger-programs
    direction): a FLAT token buffer replaces the (batch, seq) grids of
    `prefill`/`prefill_varlen`/`decode_step`/`verify_step`, so prefill
    chunks, prefix-cache suffix tails, spec-verify grids and
    single-token decodes ride the same trace — the mix changing
    between steps can never retrace, because every shape here is fixed
    by the engine's static buffer size.

    tokens: (T,) flat token ids; tok_slot: (T,) i32 owning slot;
    tok_pos: (T,) i32 ABSOLUTE cache position per row, -1 for
    inactive slack rows (their K/V lands on the trash page and the
    ragged attention kernel early-exits every page for them).
    page_table: (B, pages_per_seq) i32 snapshot. Rows must be causally
    ordered per slot within the buffer only in the sense that their
    positions are distinct — every row's K/V is scattered before
    attention, and row i reads columns < tok_pos[i]+1 (exactly
    verify_step's chunk contract, generalized).

    `sample` (traced pytree, `_sample_flat`) keeps the PR 8 device-side
    sampling contract: per-slot params gathered per row, PRNG fold =
    tok_pos + 1. `carry_tok`/`carry_gather`/`carry_mask` feed a row the
    PREVIOUS unified step's device-resident record
    (`carry_tok[carry_gather[i]]`), so the pipelined pump launches wave
    N+1 before the host has read wave N. Attention runs the pallas
    ragged paged kernel on TPU and its bit-identical jnp reference on
    CPU (paddle_tpu/kernels/ragged_paged_attention.py);
    `block_q`/`block_pages` (static) pick its tile — the engine
    resolves them ONCE at construction, so a tuned tile never retraces
    the serving trace.

    `need_rows` ((N,) i32, -1 = inactive) is the LEAN epilogue (docs/
    serving.md § Lean epilogue): the final-norm hidden states gather
    down to exactly those buffer rows BEFORE the lm_head matmul, so a
    64-token prefill chunk pays one row of unembed FLOPs and the
    (T, vocab) buffer is never materialized. Sampling rides the sparse
    rows with the row's own (tok_slot, tok_pos) — the PRNG fold does
    not move, so tokens and logprobs are bit-identical to the full
    epilogue; the returned logits and rec are N-row (the caller
    indexes them in need-row space). `cand_tok` (same leading shape as
    the epilogue rows) appends per-row filtered-distribution
    probabilities of a candidate token to the record — the spec-decode
    rejection sampler's accept tests then ride the compact record
    instead of pulling vocab rows (docs/serving.md § Speculative
    decoding).

    Returns (k_pool, v_pool, k_scale, v_scale, logits (T|N, V)[, rec]
    [, tok_buf]). `tok_buf` ((B, max_seq_len+1) i32 device ring) makes
    token values device-resident: rows gather their embedding input
    from it and decode rows (`buf_write`) scatter their sampled token
    back — the in-jit twin of the carry operands, which it replaces.
    """
    c = config
    nh, nkv = c.num_attention_heads, c.num_key_value_heads
    hd = c.hidden_size // nh
    t = tokens.shape[0]
    Pn = k_pool.shape[2]
    quant = k_scale is not None
    if carry_tok is not None:
        tokens = jnp.where(carry_mask, carry_tok[carry_gather], tokens)
    row_on = tok_pos >= 0
    pos = jnp.maximum(tok_pos, 0)
    if tok_buf is not None:
        # in-jit token source (docs/serving.md § Device token buffer):
        # column p of a slot's ring row holds the token CONSUMED at
        # cache position p, so the host ships only (slot, pos)
        # descriptors — token values (and the embedding gather below)
        # never leave the device. Subsumes the pipelined carry: wave
        # N's own scatter (bottom of this program) is device-ordered
        # before wave N+1's gather. Inactive rows read column 0 of
        # slot 0 — their K/V lands on the trash page and sampling
        # masks them, so the garbage value is never observed.
        tokens = tok_buf[tok_slot, pos]
    cos, sin = rope_cos_sin(None, hd, base=c.rope_theta,
                            position_ids=pos)            # (T, hd)
    h = jnp.take(params["embed"], tokens, axis=0)        # (T, H)

    page_ids = page_table[tok_slot, pos // page_size]
    page_ids = jnp.where(row_on, page_ids, Pn - 1)       # trash page
    off = pos % page_size

    def layer(carry, xs):
        h, kp, vp, ksp, vsp = carry
        lp, li = xs
        x = _rms(h, lp["ln1"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(t, nh, hd)
        k = (x @ lp["wk"]).reshape(t, nkv, hd)
        v = (x @ lp["wv"]).reshape(t, nkv, hd)
        q, k = apply_rotary_emb(q, k, cos[:, None], sin[:, None])
        kt = k.swapaxes(0, 1)                            # (KVH, T, D)
        vt = v.swapaxes(0, 1)
        kp, vp, ksp, vsp, kl, vl, ksl, vsl = _scatter_kv(
            kp, vp, ksp, vsp, li, page_ids, off, kt, vt, quant)
        o = ragged_paged_attention(q, kl, vl, page_table, tok_slot,
                                   tok_pos, use_pallas=use_pallas,
                                   interpret=interpret,
                                   k_scale=ksl, v_scale=vsl,
                                   block_q=block_q,
                                   block_pages=block_pages)  # (T, QH, D)
        h = h + o.reshape(t, -1).astype(h.dtype) @ lp["wo"]
        x = _rms(h, lp["ln2"], c.rms_norm_eps)
        mlp = (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
        return (h + mlp, kp, vp, ksp, vsp), None

    L = k_pool.shape[0]
    (h, k_pool, v_pool, k_scale, v_scale), _ = jax.lax.scan(
        layer, (h, k_pool, v_pool, k_scale, v_scale),
        (params["layers"], jnp.arange(L)))
    h = _rms(h, params["final_norm"], c.rms_norm_eps)
    if need_rows is not None:
        # lean epilogue: gather the needed rows FIRST — the unembed
        # matmul and everything downstream run at (N, ...), and the
        # (T, vocab) buffer never exists in this program
        idx = jnp.maximum(need_rows, 0)
        need_on = need_rows >= 0
        h = h[idx]
        tok_slot = tok_slot[idx]
        tok_pos = tok_pos[idx]
        row_on = need_on & (tok_pos >= 0)
    logits = h @ params["lm_head"]                       # (T|N, V)
    if sample is None:
        return k_pool, v_pool, k_scale, v_scale, logits
    rec = _sample_flat(logits, tok_slot, tok_pos, row_on, sample)
    if cand_tok is not None:
        rec = rec + (_cand_probs(logits, tok_slot, sample, cand_tok),)
    if tok_buf is not None:
        # scatter this wave's sampled tokens back into the ring: the
        # token sampled at position p is the one position p+1 consumes.
        # `buf_write` marks the decode rows (seed rows stay host-picked,
        # the PR 8 convention — the host pokes them at finish); masked
        # rows park on an out-of-bounds slot and drop.
        B = tok_buf.shape[0]
        wslot = jnp.where(buf_write & row_on, tok_slot, B)
        # tok_pos/tok_slot are already in epilogue space here (the lean
        # gather above re-indexed them), matching rec's rows
        pos_w = jnp.maximum(tok_pos, 0)
        tok_buf = tok_buf.at[wslot, pos_w + 1].set(
            rec[0].astype(jnp.int32), mode="drop")
        return k_pool, v_pool, k_scale, v_scale, logits, rec, tok_buf
    return k_pool, v_pool, k_scale, v_scale, logits, rec


# compile telemetry: each entry point reports compiles/retraces (new
# arg-shape signature == a fresh XLA compile) to the observability
# registry — `pt_compile_*` on /metrics, compile events in the flight
# recorder, and a retrace-storm warning when a shape churns per call
prefill = track_jit("serving.prefill")(prefill)
prefill_varlen = track_jit("serving.prefill_varlen")(prefill_varlen)
decode_step = track_jit("serving.decode_step")(decode_step)
verify_step = track_jit("serving.verify_step")(verify_step)
unified_step = track_jit("serving.unified_step")(unified_step)


# device token-ring setters (satellite of ROADMAP item 1): the two
# host-side writers of the buffer `unified_step` gathers embeddings
# from. Fixed shapes — one compile each for the life of the engine.
@jax.jit
def _tokbuf_stage(tok_buf, row_vals, slot):
    """Replace one slot's whole consumed-token row (admission, restore,
    handoff import — anywhere the sequence's history (re)enters)."""
    return tok_buf.at[slot].set(row_vals)


@jax.jit
def _tokbuf_poke(tok_buf, slot, pos, tok):
    """Write one consumed-token cell — the host-picked first token
    (PR 8 seeding convention keeps that draw host-side)."""
    return tok_buf.at[slot, pos].set(tok)


def speculative_sample(prob_rows, drafts, rng, cand_probs=None):
    """Rejection-sampled acceptance for a deterministic draft sequence
    (reference parity: speculative sampling, Leviathan et al. / the
    reference's speculative-decoding sampling path).

    prob_rows: the request's filtered sampling distributions — row g
    applies AFTER consuming chunk token g. Either a sequence of (V,)
    arrays or a callable g -> (V,) array; rows are materialized
    LAZILY, so a first-draft rejection (the common case at low
    acceptance rates) computes one row, not all n — filtering is an
    O(V log V) host sort at vocab 32k+. drafts: (n-1,) proposed tokens
    d_1..d_{n-1} (chunk tokens 1..n-1); rng: the request's
    np.random.RandomState.

    cand_probs (optional, (n-1,) floats): precomputed p_g(d_{g+1}) —
    the engine ships these as part of the device step record
    (`_cand_probs`), so the accept tests consume a float per draft and
    a row is materialized ONLY on divergence or for the final draw.
    The rng consumption order is identical with or without them: one
    rand() per accept test, one choice() per divergence/final draw.

    Accept d_{g+1} with probability p_g(d_{g+1}) (the draft proposal is
    a point mass, so min(1, p/q) = p(d)); on rejection sample from the
    renormalized residual p_g with d removed. Either way every emitted
    token is marginally distributed EXACTLY as p_g — the output
    distribution equals plain (non-speculative) sampling, while
    accepted drafts advance several tokens per verify step.

    Returns (tokens, n_accepted): up to n emitted tokens (accepted
    drafts + one final sample)."""
    row = prob_rows if callable(prob_rows) else prob_rows.__getitem__
    out = []
    n = len(drafts) + 1
    for g in range(n - 1):
        d = int(drafts[g])
        p_d = float(cand_probs[g]) if cand_probs is not None \
            else None
        if p_d is None:
            p = row(g)
            p_d = p[d]
        else:
            p = None                # materialized only on rejection
        if rng.rand() < p_d:
            out.append(d)           # accepted: token IS the draft
            continue
        if p is None:
            p = row(g)
        resid = p.copy()
        resid[d] = 0.0
        tot = resid.sum()
        if tot <= 0.0:              # p was a point mass on d — forced
            out.append(d)
            continue
        out.append(int(rng.choice(len(resid), p=resid / tot)))
        return out, g               # divergence: stop consuming drafts
    p_last = row(n - 1)
    out.append(int(rng.choice(len(p_last), p=p_last)))
    return out, n - 1


def prompt_lookup_draft(ctx, G, ngram=2):
    """Draft continuation tokens by n-gram lookup in the request's own
    context (reference parity: PaddleNLP "inference with reference" —
    speculative decoding without a draft model). Finds the most recent
    earlier occurrence of the trailing `ngram` tokens and proposes the
    up-to-G tokens that followed it. Returns [] when no match."""
    L = len(ctx)
    if L < ngram + 1:
        return []
    key = list(ctx[-ngram:])
    for i in range(L - ngram - 1, -1, -1):
        if list(ctx[i:i + ngram]) == key:
            return [int(t) for t in ctx[i + ngram:i + ngram + G]]
    return []


# ---------------------------------------------------------------------------
# engine (host-side orchestration)
# ---------------------------------------------------------------------------
class PipelineStall(RuntimeError):
    """`step_launch(carry=...)` needed a preemption victim while a step
    was still in flight. The victim's pending next_token only exists on
    device, so the caller must consume the in-flight ticket first
    (`step_finish`), then relaunch with carry=None — the drained state
    preempts exactly like the synchronous loop."""


class StepTicket:
    """One launched-but-unconsumed decode step: the device-resident
    result record plus the host metadata needed to apply it one step
    later. `reqs` maps slot -> the Request that occupied it at launch;
    `step_finish` applies a slot's result only while that identity
    still holds (a slot released/reused in between makes the in-flight
    result a discarded zombie), and marks a finishing slot's entry None
    in the NEXT ticket so its overrun token is never emitted."""

    __slots__ = ("slots", "reqs", "next_tok", "done", "logprob")

    def __init__(self, slots, reqs, next_tok, done, logprob):
        self.slots = slots          # launched slot ids, ascending
        self.reqs = reqs            # slot -> Request at launch time
        self.next_tok = next_tok    # device (B,) i32
        self.done = done            # device (B,) bool
        self.logprob = logprob      # device (B,) f32


class RaggedTicket:
    """One launched-but-unconsumed `unified_step` wave. Same contract
    as StepTicket (zombie checks, carry, eos length rollback) with the
    record FLAT: `flat` maps a decode slot to its buffer row, `seeds`
    lists (slot, req) whose prefill completed this wave — their
    first-token logits rows ride `seed_rows` and are picked HOST-side
    at finish (the PR 8 seeding convention)."""

    __slots__ = ("reqs", "flat", "next_tok", "done", "logprob",
                 "seeds", "seed_rows", "slots")

    def __init__(self, reqs, flat, next_tok, done, logprob, seeds,
                 seed_rows, slots):
        self.reqs = reqs            # slot -> Request (decode rows only)
        self.flat = flat            # slot -> flat buffer row index
        self.next_tok = next_tok    # device (T,) i32
        self.done = done            # device (T,) bool
        self.logprob = logprob      # device (T,) f32
        self.seeds = seeds          # [(slot, req)] completed prefills
        self.seed_rows = seed_rows  # device (len(seeds), V) or None
        self.slots = slots          # slots with any row this wave


class Request:
    """One generation request. Per-request sampling params (reference:
    PaddleNLP predictor SamplingParams): temperature=0 → greedy;
    top_k/top_p restrict the candidate set before sampling."""

    def __init__(self, rid, prompt_ids, max_new_tokens=64, eos_id=None,
                 temperature=0.0, top_k=0, top_p=1.0, seed=None,
                 logprobs=False):
        self.rid = rid
        self.prompt = list(prompt_ids)
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.rng = np.random.RandomState(seed) if seed is not None or \
            temperature > 0 else None
        # device-side sampling key state: the raw threefry key for
        # jax.random.PRNGKey(seed) is [seed>>32, seed&0xffffffff] —
        # built host-side (no device op at construction). The step
        # program samples with fold_in(base_key, position), so the
        # trajectory is a pure function of (seed, position): identical
        # across sync/pipelined pumps and across preemption resume.
        if self.temperature > 0:
            sk = seed if seed is not None \
                else int(np.random.randint(0, 2 ** 31 - 1))
            self._base_key = np.array(
                [(sk >> 32) & 0xFFFFFFFF, sk & 0xFFFFFFFF], np.uint32)
        else:
            self._base_key = None
        self.output = []
        self.slot = None
        self.next_token = None
        # prompt tokens served from the prefix KV cache at admission
        # (0 for cold admissions; surfaced in the HTTP usage block)
        self.cached_tokens = 0
        # runtime accounting (paddle_tpu.serving): cancellation flag is
        # honored at step boundaries; timestamps feed TTFT/TPOT metrics
        self.cancelled = False
        self._t_submit = None
        self._t_first = None
        self._t_last = None
        # logprobs=True: record log p(token | context) under the RAW
        # model distribution for every emitted token (reference parity:
        # the predictor's return_full_hidden/logprob outputs; vLLM
        # convention — raw softmax, not the filtered sampling dist)
        self.want_logprobs = bool(logprobs)
        self.logprobs = [] if logprobs else None

    def pick(self, logits_row):
        """Select the next token from this request's logits row."""
        from .generation import sample_logits_np
        return sample_logits_np(logits_row, self.temperature, self.top_k,
                                self.top_p, self.rng)

    def note_logprob(self, tok, logits_row):
        """Record the raw-model logprob of an emitted token."""
        if not self.want_logprobs:
            return
        x = np.asarray(logits_row, np.float64)
        x = x - x.max()
        self.logprobs.append(
            float(x[tok] - np.log(np.exp(x).sum())))

    @property
    def done(self):
        return (len(self.output) >= self.max_new_tokens or
                (self.eos_id is not None and self.output and
                 self.output[-1] == self.eos_id))


def _tl_mark(req, name):
    """Stamp an exceptional transition (preempted/resumed, spill/
    restore, handoff_export/import) on the request's timeline ledger.
    The scheduler attaches `req._timeline` (serving/timeline.py); bare
    engines and PT_SERVE_TIMELINE=0 leave it absent and this is a
    no-op. Host clock only — the timeline plane must never add device
    traffic to the step loop."""
    tl = getattr(req, "_timeline", None)
    if tl is not None:
        tl.mark(name)


def _tl_count(req, phase, n=1):
    """Bump the request's per-phase step counter (same ledger)."""
    tl = getattr(req, "_timeline", None)
    if tl is not None:
        tl.count(phase, n)


class ServingEngine:
    """Continuous-batching decode loop over the paged cache.

    Admission control (reference: PaddleNLP predictor scheduling +
    vLLM-style paged serving): submit() rejects requests that can never
    fit max_seq_len with a clear error; requests that fit but exceed
    CURRENT capacity queue until slots/pages free up. `num_pages`
    (default: worst-case max_seqs*pages_per_seq) may oversubscribe the
    pool; if decode then runs out of pages, the most-recently admitted
    request is preempted — its pages return to the pool and it re-enters
    the head of the queue (no re-sampling of tokens it already emitted).

    `preempt_policy` selects how an evicted request resumes (reference
    parity: fleet BlockManager swap-out/swap-in in
    paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu's
    serving stack):
      * "offload" (default): the victim's KV pages are copied to HOST
        memory on eviction and scattered back into fresh device pages on
        resume — zero recompute, one device<->host round trip of
        n_pages*page_size tokens of KV.
      * "recompute": pages are dropped; resume re-prefills
        prompt + generated-so-far (cheaper on host RAM, ~1 extra prefill
        of compute per eviction).

    `prefix_cache=True` (serving/kvcache.py; docs/serving.md § Prefix
    caching) indexes full KV pages by a chained block hash of their
    token ids: admissions sharing a prompt prefix map the same
    physical pages (ref-counted via the PagePool every page-lifetime
    path runs through) and prefill ONLY their suffix — lengths are
    pre-seeded to the cached token count and the suffix runs as one
    bucket-shaped verify_step chunk over the cached pages. Refcount-0
    pages that are still indexed park in an LRU that allocation
    reclaims before the pool is declared empty.

    Sampling and stop-condition evaluation run INSIDE the jitted step
    (docs/serving.md § Pipelined step loop): `decode_step` takes every
    sampling parameter as a traced per-slot array plus a per-slot PRNG
    key (fold_in(seed_key, position)) and returns a compact
    (next_token, done, logprob) record — the host transfer is a few
    ints per slot, never a `[vocab]` row. `step_launch`/`step_finish`
    split the step so a pipelined driver (the scheduler's
    double-buffered pump, or `run_pipelined`) can consume step N's
    record while step N+1 — fed step N's tokens directly from the
    device record — is already running.

    `host_tier_bytes>0` (serving/kvtier.py; docs/serving.md § KV-cache
    tiering) adds a bounded host-RAM tier under that LRU: evictions
    demote their pages (async device->host copy off the pump thread,
    int8-quantized with per-token fp32 scales unless
    tier_quantize=False) instead of discarding them, admission lookups
    fall through device -> host, and tier hits are restored into fresh
    device pages so a returning multi-turn conversation prefills only
    its genuinely new tokens. The preemption offload stash shares the
    tier's bytes ledger regardless of the budget."""

    def __init__(self, params, config: LlamaConfig, max_seqs=4,
                 max_seq_len=512, page_size=16, dtype=jnp.float32,
                 use_pallas=None, interpret=False, num_pages=None,
                 cache_dtype=None, preempt_policy="offload",
                 spec_decode=0, spec_ngram=2, chunked_prefill=False,
                 spec_sample=False, mesh=None, prefix_cache=False,
                 host_tier_bytes=0, tier_quantize=True, faults=None,
                 ragged=None, ragged_tokens=None, lean=None,
                 block_q=None, block_pages=None, tokbuf=None):
        c = config
        _wire_compile_cache()
        # mesh with a 'tp' axis: tensor-parallel serving — weights get
        # megatron NamedShardings (llama_spmd.param_specs), the KV pool
        # shards over its KV-head axis, the paged kernels run per-rank
        # under shard_map (_attn_tp) and everything else partitions via
        # GSPMD. Admission/eviction logic is untouched: page_table and
        # lengths stay replicated host-visible arrays. This is how a
        # model larger than one chip serves (reference: fleet TP under
        # the predictor, mp_layers.py + block_multihead_attention).
        self._mesh = None
        if mesh is not None and mesh.shape.get("tp", 1) > 1:
            tp = mesh.shape["tp"]
            if c.num_attention_heads % tp or c.num_key_value_heads % tp:
                raise ValueError(
                    f"tp={tp} must divide num_attention_heads="
                    f"{c.num_attention_heads} and num_key_value_heads="
                    f"{c.num_key_value_heads} (degenerate GQA shardings "
                    "are not supported)")
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            from . import llama_spmd as _spmd
            params = _spmd.place_params(params, c, mesh, pp=False)
            self._mesh = mesh
            self._pool_sharding = NamedSharding(mesh, P(None, "tp"))
            self._repl_sharding = NamedSharding(mesh, P())
        self.params = params
        self.config = c
        self.page_size = page_size
        self.max_seqs = max_seqs
        self.max_seq_len = max_seq_len
        self.pages_per_seq = -(-max_seq_len // page_size)
        # +1 trash page for masked writes of inactive slots
        if num_pages is None:
            num_pages = max_seqs * self.pages_per_seq + 1
        else:
            num_pages = int(num_pages)
            if num_pages < self.pages_per_seq + 1:
                raise ValueError(
                    f"num_pages={num_pages} cannot hold even one "
                    f"max_seq_len sequence ({self.pages_per_seq} pages) "
                    "+ the trash page")
        if cache_dtype not in (None, "int8", jnp.int8):
            # a silently-wrong pool dtype (e.g. 'int4', or a typo)
            # would truncate K/V writes with no scales and decode
            # garbage — fail at construction, not mid-decode
            raise ValueError(
                f"cache_dtype={cache_dtype!r} unsupported: use 'int8' "
                "(quantized pool + per-token scales) or None (pool "
                "stores `dtype`)")
        if preempt_policy not in ("offload", "recompute"):
            raise ValueError(
                f"preempt_policy={preempt_policy!r}: use 'offload' "
                "(host-swap KV pages) or 'recompute' (re-prefill)")
        self.preempt_policy = preempt_policy
        self.preemptions = 0
        self.prefill_tokens = 0  # total tokens ever run through prefill
        # speculative decoding (reference: PaddleNLP speculative /
        # "inference with reference"): spec_decode = chunk width G —
        # each device step verifies 1 pending + up to G-1 prompt-lookup
        # drafted tokens for greedy requests. 0/1 = plain decode.
        self.spec_decode = int(spec_decode)
        self.spec_ngram = int(spec_ngram)
        if self.spec_decode < 0:
            raise ValueError(f"spec_decode={spec_decode}: want >= 0")
        # chunked prefill (reference parity: PaddleNLP/vLLM split-fuse):
        # admissions feed their prompt G tokens per verify step instead
        # of one monolithic prefill, so decoding requests never stall
        # behind a long prompt. Rides the spec verify chunk — needs
        # spec_decode >= 2 (G is the chunk width).
        self.chunked_prefill = bool(chunked_prefill)
        if self.chunked_prefill and self.spec_decode < 2:
            raise ValueError(
                "chunked_prefill rides the spec verify chunk: set "
                "spec_decode >= 2 (the chunk width)")
        # spec_sample: draft for SAMPLED requests too, accepted by
        # rejection sampling (speculative_sample) — the output
        # DISTRIBUTION equals plain sampling exactly, but the rng
        # consumption (hence the seeded trajectory) differs from the
        # non-speculative engine, so it is opt-in
        self.spec_sample = bool(spec_sample)
        if self.spec_sample and self.spec_decode < 2:
            raise ValueError("spec_sample needs spec_decode >= 2")
        self.spec_drafted = 0    # draft tokens fed to verify
        self.spec_accepted = 0   # draft tokens accepted
        self.device_steps = 0    # decode/verify device calls
        # unified ragged step (docs/serving.md § Unified ragged step):
        # every device dispatch — admission prefills, prefix-cache
        # suffix tails, spec-verify grids, single-token decodes — rides
        # ONE jitted `unified_step` over a flat token buffer, so the
        # prefill/decode mix changing between steps can never retrace
        # and no token row is bucket padding. Default ON; the bucketed
        # entry points remain as the PT_SERVE_RAGGED=0 fallback for one
        # release. Tensor-parallel engines stay bucketed (the ragged
        # pallas kernel has no shard_map wrapper yet).
        if ragged is None:
            ragged = os.environ.get("PT_SERVE_RAGGED", "1") \
                not in ("", "0") and self._mesh is None
        self.ragged = bool(ragged)
        if self.ragged and self._mesh is not None:
            raise ValueError(
                "ragged=True does not run under tensor parallelism yet "
                "— build the engine with ragged=False (or "
                "PT_SERVE_RAGGED=0) to keep the bucketed entry points")
        G_ = max(self.spec_decode, 1)
        if ragged_tokens is None:
            ragged_tokens = 1 << math.ceil(
                math.log2(max(max_seqs * G_, 16)))
        self.ragged_buf = int(ragged_tokens)
        if self.ragged and self.ragged_buf < max_seqs * G_:
            raise ValueError(
                f"ragged_tokens={self.ragged_buf} cannot hold one "
                f"row per slot ({max_seqs} slots x chunk width {G_}) — "
                "a full wave would not fit the flat buffer")
        # padding-waste telemetry (pt_pad_tokens_total /
        # pt_ragged_tokens_total via EngineMetrics.on_step): pad counts
        # power-of-two bucket padding rows dispatched by the bucketed
        # prefill sites (`_bucket_for`); ragged counts REAL rows served
        # through `unified_step` — buffer slack rows are skipped
        # capacity (the kernel's early exit), not dispatched padding
        self.pad_tokens = 0
        self.ragged_tokens = 0
        # lean row-sparse lm_head epilogue (docs/serving.md § Lean
        # epilogue): every unified/verify dispatch passes a `need_rows`
        # descriptor and the (T, vocab) logits buffer is never
        # materialized — only the rows a wave actually samples, seeds,
        # or rejection-tests pay unembed FLOPs. Token- and logprob-
        # identical to the full epilogue; default ON (PT_SERVE_LEAN=0
        # or lean=False restores full logits for A/B baselines).
        if lean is None:
            lean = os.environ.get("PT_SERVE_LEAN", "1") not in ("", "0")
        self.lean = bool(lean)
        # the lean need-row buffer: a wave needs at most one sampled
        # row per decoding slot (x chunk width G under spec) plus one
        # seed row per prefilling slot — and a slot is never both, so
        # max_seqs * G bounds it. Fixed shape => zero retrace as the
        # mix changes.
        self.need_buf = max_seqs * G_
        # pt_logit_rows_total / pt_logit_rows_skipped_total telemetry:
        # unembed rows actually computed vs rows the lean epilogue
        # avoided (full engines skip nothing)
        self.logit_rows = 0
        self.logit_rows_skipped = 0
        # ragged kernel tile (docs/tuning.md § Serving kernel
        # autotune): constructor args win, else the per-TPU-generation
        # winner persisted by tools/tune_ragged.py, else the seed
        # shape. Resolved ONCE here — a static jit arg, so the tile
        # never retraces the serving trace mid-flight.
        tq, tp_ = _tuning.load_ragged_tile(device_generation())
        if block_q is None:
            block_q = tq
        if block_pages is None:
            block_pages = tp_
        self._block_q = int(block_q) or None
        self._block_pages = int(block_pages) or None
        # device-resident token ring (ROADMAP item-1 last follow-on):
        # (max_seqs, max_seq_len+1) i32 where column p holds the token
        # a slot CONSUMES at cache position p. `unified_step` gathers
        # its embedding input from it (host ships only slot/pos
        # descriptors) and scatters each wave's sampled tokens back
        # in-jit, replacing the pipelined-carry operands. Host writes
        # ride two fixed-shape jitted setters (`_tokbuf_stage` at
        # admission/restore/import, `_tokbuf_poke` for host-picked
        # seeds) — zero retrace. Ragged plain-decode engines only: the
        # spec verify chunk keeps host-fed token values.
        # PT_SERVE_TOKBUF=0 (or tokbuf=False) restores the host token
        # path for A/B baselines.
        if tokbuf is None:
            tokbuf = os.environ.get("PT_SERVE_TOKBUF", "1") \
                not in ("", "0")
        self.tok_buf = jnp.zeros((max_seqs, max_seq_len + 1), jnp.int32) \
            if tokbuf and self.ragged and self.spec_decode <= 1 else None
        # optional telemetry sink (paddle_tpu.serving.metrics
        # EngineMetrics duck type): the step loop reports TTFT/TPOT,
        # occupancy, page stats, and preemptions into it. None = free.
        self.metrics = None
        self._order = 0
        kvh = c.num_key_value_heads
        hd = c.hidden_size // c.num_attention_heads
        L = c.num_hidden_layers
        # cache_dtype="int8": quantized KV pool with per-token fp32
        # scales (reference parity: cachekv-quant decode in
        # phi/kernels/fusion/gpu/block_attn.h) — 2x (bf16) / ~3.5x
        # (fp32, net of scales) the servable tokens per pool byte
        self.cache_quant = cache_dtype in ("int8", jnp.int8)
        pool_dtype = jnp.int8 if self.cache_quant else \
            (cache_dtype or dtype)
        self.num_pages = num_pages
        pshape = (L, kvh, num_pages, page_size, hd)
        self.k_pool = jnp.zeros(pshape, pool_dtype)
        self.v_pool = jnp.zeros(pshape, pool_dtype)
        if self.cache_quant:
            self.k_scale = jnp.zeros(pshape[:-1] + (1,), jnp.float32)
            self.v_scale = jnp.zeros(pshape[:-1] + (1,), jnp.float32)
        else:
            self.k_scale = self.v_scale = None
        # page_table/lengths are HOST numpy state, transferred once per
        # device call: the admission/growth bookkeeping reads and writes
        # them element-wise every step, and each element access on a
        # device array is a blocking host<->device round trip (~31 eager
        # dispatches per step measured on CPU; on TPU each is a tunnel
        # latency) — the whole tables are a few hundred bytes, so one
        # jnp.asarray per step is strictly cheaper
        # unassigned entries point at the trash page, never page 0: a
        # stale or default row must alias a page no live slot reads
        self.page_table = np.full((max_seqs, self.pages_per_seq),
                                  self.num_pages - 1, np.int32)
        self.lengths = np.zeros((max_seqs,), np.int32)
        if self._mesh is not None:
            self.k_pool = jax.device_put(self.k_pool, self._pool_sharding)
            self.v_pool = jax.device_put(self.v_pool, self._pool_sharding)
            if self.cache_quant:
                self.k_scale = jax.device_put(self.k_scale,
                                              self._pool_sharding)
                self.v_scale = jax.device_put(self.v_scale,
                                              self._pool_sharding)
        # single ref-count-aware allocator for EVERY page-lifetime path
        # (admission, finish, cancel sweep, offload/restore). The trash
        # page (last id) is outside the pool: never allocated, shared,
        # indexed, or evicted. prefix_cache=True additionally indexes
        # full pages by chained block hash so admissions sharing a
        # prompt prefix map the same physical pages and prefill only
        # their suffix (serving/kvcache.py; docs/serving.md).
        self.prefix_cache = PrefixCache(page_size) if prefix_cache else None
        self.pool = PagePool(num_pages - 1, cache=self.prefix_cache)
        # host-RAM KV tier (serving/kvtier.py; docs/serving.md
        # § KV-cache tiering): one budgeted ledger for ALL
        # host-resident KV. The preemption offload stash always lives
        # here; with host_tier_bytes > 0 the prefix cache's LRU
        # evictions additionally DEMOTE their pages into it (async
        # device->host copy off the pump thread, int8-quantized with
        # per-token scales unless tier_quantize=False) and admission
        # lookups fall through device -> host, restoring hits into
        # fresh device pages. Disabled spill — the default — keeps
        # seed behavior exactly.
        if host_tier_bytes and not prefix_cache:
            raise ValueError(
                f"host_tier_bytes={host_tier_bytes} needs "
                "prefix_cache=True: only the prefix cache's evictions "
                "feed the spill tier")
        self.host_tier = HostTier(page_size, tier_bytes=host_tier_bytes,
                                  quantize=tier_quantize)
        # deterministic fault injection (serving/faults.py;
        # docs/reliability.md): a seeded plan armed at the stack's real
        # failure sites, via constructor or PT_FAULTS. None (the
        # default when the env var is unset) costs nothing and
        # preserves seed behavior exactly. `restarts` counts
        # crash_reset() warm restarts — the scheduler's recovery path.
        self.faults = faults if faults is not None else FaultPlan.from_env()
        self.host_tier.faults = self.faults
        self.restarts = 0
        # disaggregated prefill/decode handoff (serving/handoff.py;
        # docs/serving.md § Disaggregated prefill/decode): a request
        # submitted with `_handoff_export` set finishes with its KV
        # pages exported as a KVHandoff instead of decoding here.
        # Counters mirror to pt_handoff_* via EngineMetrics.on_step;
        # `_handoff_times` is drained into the pt_handoff_seconds
        # histogram there (both on the pump thread — single-writer).
        # `_handoff_pending` is a fast-path guard for the per-launch
        # harvest scan: 0 (the role="both" default) costs one int
        # compare per step and constructs nothing.
        self.handoff_exports = 0
        self.handoff_imports = 0
        self.handoff_bytes = 0
        self.handoff_failures = 0
        self._handoff_times = []
        self._handoff_pending = 0
        if self.prefix_cache is not None:
            self.prefix_cache.on_evict = self._note_prefix_evict
            if self.host_tier.enabled:
                self.prefix_cache.on_spill = self._spill_page
        self._index_suspend = False  # set while releasing failed slots
        self._seq_pages = {s: [] for s in range(max_seqs)}
        self._slots = [None] * max_seqs          # slot -> Request
        # occupied-slot set maintained by admit/release: the per-step
        # page-growth and batch-building passes iterate THIS, not all
        # max_seqs slots (a 256-slot engine at occupancy 3 was paying
        # a 256-iteration host scan per step)
        self._live = set()
        self._waiting = []
        self.finished = []
        # step-loop launch telemetry: wall time between consecutive
        # decode/verify dispatches (pt_step_host_gap_seconds) and how
        # many launched steps the host has not yet consumed
        # (pt_pipeline_depth: 1 under the double-buffered pump)
        self._t_launch_end = None
        self.last_host_gap_s = 0.0
        self.pipeline_depth = 0
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self._use_pallas = use_pallas
        # prefill under tp runs the jnp attention (GSPMD partitions it
        # over heads automatically); only the paged decode/verify
        # kernels get the manual shard_map region. Prefill is
        # matmul-bound, so XLA's fused attention is near-parity there —
        # the pallas win is the decode path's page streaming.
        self._use_pallas_prefill = False if self._mesh is not None \
            else use_pallas
        self._interpret = interpret

    @property
    def _free(self):
        """The pool's free list (compatibility view — tests and tools
        poke it directly; engine code goes through `self.pool`)."""
        return self.pool.free

    @_free.setter
    def _free(self, pages):
        self.pool.free = list(pages)

    # -- request admission ------------------------------------------------
    def validate(self, req: Request):
        """Raise ValueError for a request that could NEVER run (clear
        engine-level error instead of a deep PagedKVCache failure
        mid-decode). Separated from submit() so frontends can
        admit-or-refuse before queueing."""
        S = len(req.prompt)
        if S == 0:
            raise ValueError("serving: empty prompt")
        if S + req.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"serving: prompt ({S} tokens) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_seq_len="
                f"{self.max_seq_len}; truncate the prompt, lower "
                "max_new_tokens, or build the engine with a larger "
                "max_seq_len")

    def submit(self, req: Request):
        """Validate-or-reject now; queue what fits."""
        self.validate(req)
        if req._t_submit is None:
            req._t_submit = time.perf_counter()
        if getattr(req, "_handoff_export", False):
            self._handoff_pending += 1
        self._waiting.append(req)
        m = self.metrics
        if m is not None:
            m.on_submit(self)

    def cancel(self, req: Request):
        """Cancel a queued or active request: queued requests leave
        the waiting queue immediately; an active slot is released (its
        pages return to the pool) at the next step() boundary. Either
        way the request lands in `finished` with req.cancelled=True
        and whatever output it already produced. NOT thread-safe —
        call from the thread driving step() (the scheduler's pump).
        Returns True if the request was queued or active."""
        req.cancelled = True
        if req in self._waiting:
            self._waiting.remove(req)
            self._drop_offload(req)
            self._clear_handoff_flag(req)
            self.finished.append(req)
            m = self.metrics
            if m is not None:
                m.on_cancel("queued")
            return True
        return req.slot is not None

    def _sweep_cancelled(self):
        """Release slots (and drop queued entries) whose requests were
        cancelled since the last step."""
        m = self.metrics
        for s in sorted(self._live):
            r = self._slots[s]
            if r is not None and r.cancelled:
                self.finished.append(r)
                self._release(s)
                r.slot = None
                if m is not None:
                    m.on_cancel("active")
        if any(r.cancelled for r in self._waiting):
            keep = []
            for r in self._waiting:
                if r.cancelled:
                    self._drop_offload(r)
                    self._clear_handoff_flag(r)
                    self.finished.append(r)
                    if m is not None:
                        m.on_cancel("queued")
                else:
                    keep.append(r)
            self._waiting = keep

    def _note_emit(self, req: Request, n: int):
        """Token-emission accounting: first emission closes the TTFT
        clock (from submit, queueing included), later ones feed the
        per-token latency histogram."""
        m = self.metrics
        if m is None or n <= 0:
            return
        _tl_count(req, "decode")
        now = time.perf_counter()
        if req._t_first is None:
            req._t_first = now
            if req._t_submit is not None:
                m.observe_ttft(now - req._t_submit)
        elif req._t_last is not None:
            m.observe_tpot((now - req._t_last) / n)
        req._t_last = now
        m.on_tokens(n)

    def _note_finish(self, req: Request):
        m = self.metrics
        if m is not None:
            dt = None if req._t_submit is None \
                else time.perf_counter() - req._t_submit
            m.on_finish(req, dt)

    def _note_step(self, n_active: int):
        m = self.metrics
        if m is not None:
            m.on_step(self, n_active)

    def _attach(self, slot, req):
        """Single site that occupies a slot — keeps the live-slot set
        in sync with `_slots` (release is the only other mutator)."""
        self._slots[slot] = req
        self._live.add(slot)

    def _stage_tokbuf(self, slot, req):
        """(Re)write one slot's device token-ring row: everything the
        sequence has consumed or holds pending — prompt + output (the
        pending next_token is always output's tail) — zero-padded to
        the fixed row shape. One call per (re)admission; no-op when
        the engine runs the host token path."""
        if self.tok_buf is None:
            return
        vals = np.zeros((self.max_seq_len + 1,), np.int32)
        toks = list(req.prompt) + [int(t) for t in req.output]
        n = min(len(toks), self.max_seq_len + 1)
        vals[:n] = toks[:n]
        self.tok_buf = _tokbuf_stage(self.tok_buf, vals, np.int32(slot))

    def _fetch_results(self, tree):
        """The ONE sanctioned device->host read in the serving step
        loop (tpulint config `sanctioned_sync`): everything the host
        needs from a device step — the per-slot (next_token, done,
        logprob) records, spec verify grids, sampling rows, admission
        seed rows — rides ONE batched transfer. Under the pipelined
        pump this read is issued one step behind the launch, so it
        overlaps the next device step instead of stalling it."""
        return jax.device_get(tree)

    def _spec_row_dist(self, logits, idx, req):
        """Materialize ONE filtered sampling distribution row for the
        spec rejection sampler's divergence/final draws (docs/serving.md
        § Speculative decoding). The filter (`_spec_dist_rows`) runs on
        device over a fixed (1, V) shape — one compile for the whole
        serve — and the row crosses via the sanctioned `_fetch_results`
        read. The common accepted-draft case never calls this: accept
        tests ride the step record's candidate probabilities.
        Renormalized in float64 so np.random.choice's sum-to-1 check
        passes on a float32 softmax row."""
        row = _spec_dist_rows(
            logits[jnp.asarray(idx, jnp.int32)][None],
            jnp.full((1,), req.temperature, jnp.float32),
            jnp.full((1,), req.top_k, jnp.int32),
            jnp.full((1,), req.top_p, jnp.float32))
        p = self._fetch_results(row)[0].astype(np.float64)
        return p / p.sum()

    def _fire(self, point, value=None, rids=None):
        """Fault-injection hook (serving/faults.py): no-op unless a
        FaultPlan is attached; an armed rule may raise, sleep, or
        corrupt `value` here — at the stack's real failure site."""
        f = self.faults
        if f is None:
            return value
        return f.fire(point, value, rids=rids)

    def crash_reset(self):
        """The engine half of a warm restart, after a step exception:
        release every slot exactly as a failure must (prefix indexing
        SUSPENDED — a failed step's K/V may be partial; slots that were
        mid-admission when the exception hit still hold pages but no
        Request, so the sweep keys on either), drop engine-queued
        work's host-stashed KV, and clear the launch telemetry clock.
        What happens to the REQUESTS (requeue / quarantine / fail) is
        the scheduler's decision — this only returns the engine to a
        cleanly-empty, immediately servable state. Returns the requests
        that were engine-queued at the crash."""
        self.restarts += 1
        self._t_launch_end = None
        self._index_suspend = True
        try:
            for s in range(self.max_seqs):
                if self._slots[s] is not None or self._seq_pages[s]:
                    self._release(s)
        finally:
            self._index_suspend = False
        for r in self._waiting:
            self._drop_offload(r)
        waiting, self._waiting = self._waiting, []
        # requeued requests keep their export flags; re-submission
        # re-counts them, so the pending counter restarts from zero
        self._handoff_pending = 0
        return waiting

    @staticmethod
    def _feed_ids(req):
        """Tokens to prefill: the original prompt, plus — after a
        preemption — everything already generated except the pending
        next_token (which was sampled but not yet fed to the cache)."""
        if getattr(req, "_resume", False):
            return list(req.prompt) + [int(t) for t in req.output[:-1]]
        return list(req.prompt)

    def _bucket_for(self, n):
        """The power-of-two padding bucket for an n-token bucketed
        dispatch — ONE definition for the monolithic prefill, the
        packed varlen prefill and the suffix-prefill chunk (they used
        to recompute it independently). Reports the choice to compile
        telemetry (`set_context(bucket=...)` rides the NEXT tracked
        call's flight "compile" record, so a retrace storm names the
        bucket that caused it) and counts the `b - n` padding rows into
        `pt_pad_tokens_total` — the waste the ragged step eliminates."""
        b = max(self.page_size, 1 << math.ceil(math.log2(max(n, 1))))
        self.pad_tokens += b - n
        _compile.set_context(bucket=b)
        return b

    def _admit(self):
        """Admit all waiting requests that fit — ONE varlen prefill call
        for the whole ragged batch (no per-sequence dense fallback)."""
        free_slots = [s for s in range(self.max_seqs)
                      if self._slots[s] is None]
        # admit only what both slots AND kv pages can hold — popping a
        # request we cannot scatter would silently drop it
        # reserve pages that active slots will need at this step —
        # otherwise an admission can fill the pool and become the
        # immediate preemption victim (full prefill wasted). Plain
        # decode grows one page exactly at a boundary; a spec verify
        # chunk can need pages for up to G new positions at once.
        if self.spec_decode > 1 or self.ragged:
            G = max(self.spec_decode, 1)
            def _reserve(s):
                r = self._slots[s]
                if self._prefilling(r):
                    # keep a mid-prefill slot's whole remaining prompt
                    # reserved (lazily allocated, but spoken for):
                    # admitting a second long prompt into pages the
                    # first will certainly need would just thrash
                    # admit -> evict cycles
                    horizon = len(r._pf_feed)
                else:
                    horizon = min(int(self.lengths[s]) + G,
                                  self.max_seq_len)
                return max(0, -(-horizon // self.page_size)
                           - len(self._seq_pages[s]))
            growth_need = sum(_reserve(s) for s in sorted(self._live))
        else:
            growth_need = sum(
                1 for s in self._live
                if int(self.lengths[s]) > 0
                and int(self.lengths[s]) % self.page_size == 0
                and len(self._seq_pages[s]) * self.page_size
                <= int(self.lengths[s]))
        reserve = growth_need
        take = 0
        for req in self._waiting[:len(free_slots)]:
            ofl = getattr(req, "_offload", None)
            hin = getattr(req, "_kv_import", None)
            if ofl is not None:
                need = ofl["pages"]
                if ofl["len"] % self.page_size == 0 and \
                        need * self.page_size <= ofl["len"]:
                    need += 1  # boundary growth this same step
            elif hin is not None:
                # a handoff import scatters its shipped pages like a
                # restore — no prefix probe (the payload IS the prefix)
                need = hin.pages
                if hin.length % self.page_size == 0 and \
                        need * self.page_size <= hin.length:
                    need += 1
            else:
                feed = self._feed_ids(req)
                feed_len = max(len(feed), 1)
                # acquire the cached prefix NOW (ref-counted) so a
                # later candidate's allocation cannot evict it out
                # from under this one; `need` then counts only the
                # UNCACHED pages — cache-aware admission accounting
                req._kv_match = self._cache_acquire(feed, req)
                need = -(-feed_len // self.page_size) \
                    - len(req._kv_match[0])
                if feed_len % self.page_size == 0:
                    need += 1  # its own first decode boundary, same step
            # pool.available() counts free + reclaimable (rc==0 cached)
            # pages; reviving a matched page above already removed it
            # from the reclaimable side
            if need > self.pool.available() - reserve:
                self._cache_unacquire(req)
                break
            reserve += need
            take += 1
        if take == 0:
            return
        all_reqs = [self._waiting.pop(0) for _ in range(take)]
        all_slots = free_slots[:take]
        _flight.record(
            "engine.admit", rids=[str(r.rid) for r in all_reqs],
            resumed=sum(1 for r in all_reqs
                        if getattr(r, "_offload", None) is not None),
            free_pages=len(self._free))
        # host-offloaded victims resume by scattering their saved pages
        # back — no prefill compute; everything else joins one varlen
        # prefill batch (or, under chunked_prefill, starts feeding its
        # prompt G tokens per verify step so decoders never stall)
        reqs, slots = [], []
        for slot, req in zip(all_slots, all_reqs):
            if getattr(req, "_resume", False):
                # swap-in / recompute-resume / crash-recovery re-admit:
                # one timeline mark regardless of which path below runs
                _tl_mark(req, "resumed")
            match = getattr(req, "_kv_match", None) or ([], 0)
            req._kv_match = None
            if getattr(req, "_offload", None) is not None:
                self._restore_into(slot, req)
            elif getattr(req, "_kv_import", None) is not None and \
                    self._import_handoff(slot, req):
                pass  # scattered + attached; failure fell through below
            elif self.chunked_prefill or self.ragged:
                req._pf_feed = self._feed_ids(req)
                req._pf_cursor = 0
                # seed the first token iff it was never seeded: a
                # resumed DECODING request keeps its pending next_token
                # (output non-empty), while a fresh request or a victim
                # evicted mid-prefill (output still empty) needs one
                req._pf_sample = not req.output
                req._resume = False
                req.slot = slot
                req._admit_order = self._order
                self._order += 1
                self._attach(slot, req)
                self._stage_tokbuf(slot, req)
                if match[0]:
                    # cached prefix: map the shared pages in and start
                    # the chunk feed at the first uncached token
                    self._map_prefix(slot, match)
                    req._pf_cursor = match[1]
                self._note_prefix_admit(req, match)
            elif match[0]:
                self._prefill_suffix_into(slot, req, match)
            else:
                self._note_prefix_admit(req, match)
                reqs.append(req)
                slots.append(slot)
        take = len(reqs)
        if take == 0:
            return
        if take == 1:
            self._prefill_into(slots[0], reqs[0])
            return
        feeds = [self._feed_ids(r) for r in reqs]
        for r in reqs:
            _tl_count(r, "prefill")
        lens = [len(f) for f in feeds]
        total = sum(lens)
        self.prefill_tokens += total
        bucket = self._bucket_for(total)
        ids = np.zeros((bucket,), np.int64)
        cu = np.zeros((self.max_seqs + 1,), np.int32)
        off = 0
        for i, f in enumerate(feeds):
            ids[off:off + lens[i]] = f
            off += lens[i]
            cu[i + 1] = off
        cu[take + 1:] = off  # unused tail: zero-length segments
        # `prefill_varlen`'s epilogue is already row-sparse (one final
        # row per packed segment)
        self.logit_rows += self.max_seqs
        with record_span("serving.prefill"):
            logits, k_all, v_all = prefill_varlen(
                self.params, jnp.asarray(ids), jnp.asarray(cu),
                self.config, use_pallas=self._use_pallas_prefill,
                interpret=self._interpret)
        # ONE bucket-shaped scatter for the whole packed buffer: per-
        # request slices would give every distinct prompt length its own
        # scatter shape, and each shape is a fresh XLA compile (~100 ms
        # on CPU, a tunnel round-trip on TPU) — measured 96 compiles in
        # 65 steps before this, drowning steady-state decode
        pg, off = self._packed_indices(k_all.shape[2])
        # every admitted request's first-token logits row comes over in
        # one batched read through the engine's sanctioned reader —
        # np.asarray(logits[i]) inside the loop was a blocking round
        # trip per admission (tpulint TPL001)
        seed_idx = [i for i, req in enumerate(reqs)
                    if not getattr(req, "_resume", False)]
        seed_rows = dict(zip(seed_idx, self._fetch_results(
            logits[jnp.asarray(seed_idx, jnp.int32)]))) \
            if seed_idx else {}
        for i, (slot, req) in enumerate(zip(slots, reqs)):
            a = int(cu[i])
            self._fill_indices(pg, off, slot, a, lens[i])
            req.slot = slot
            req._admit_order = self._order
            self._order += 1
            self._attach(slot, req)
            # index BEFORE seeding: a max_new_tokens==1 request
            # finishes (and releases) inside _seed_first_token
            self._index_slot(slot, req)
            if getattr(req, "_resume", False):
                # resuming after preemption: next_token was already
                # sampled before eviction — do NOT re-sample it
                req._resume = False
            else:
                self._seed_first_token(slot, req, seed_rows[i])
        self._scatter_packed(k_all, v_all, pg, off)

    def _packed_indices(self, t):
        """Fresh (page, offset) index arrays of length t, pointing at
        the trash page — bucket-static shapes keep the scatter compile
        count at one per bucket."""
        pg = np.full((t,), self.num_pages - 1, np.int32)
        off = (np.arange(t) % self.page_size).astype(np.int32)
        return pg, off

    def _fill_indices(self, pg, off, slot, start, S):
        """Point positions start..start+S at slot's freshly-allocated
        pages and set its length."""
        n_pages = -(-S // self.page_size)
        self._seq_pages[slot] = []
        pages = self._alloc_pages(slot, n_pages)
        pos = np.arange(S)
        pg[start:start + S] = np.asarray(pages)[pos // self.page_size]
        off[start:start + S] = pos % self.page_size
        self.lengths[slot] = S

    def _scatter_packed(self, kq, vq, pg, off):
        """Scatter packed per-layer K/V (L, KVH, T, D) into the pools
        at (pg, off) — trash-page tail positions absorb the padding."""
        if self.cache_quant:
            kq, ks = quantize_kv(kq)
            vq, vs = quantize_kv(vq)
            self.k_scale = self.k_scale.at[:, :, pg, off].set(ks)
            self.v_scale = self.v_scale.at[:, :, pg, off].set(vs)
        self.k_pool = self.k_pool.at[:, :, pg, off].set(
            kq.astype(self.k_pool.dtype))
        self.v_pool = self.v_pool.at[:, :, pg, off].set(
            vq.astype(self.v_pool.dtype))

    def _scatter_prompt(self, slot, kq, vq, S):
        """Scatter one prompt's per-layer K/V (L, KVH, T>=S, D) into
        fresh pages for `slot`; positions past S land on the trash
        page (pass the PADDED buffer — slicing to S would recompile
        per prompt length)."""
        pg, off = self._packed_indices(kq.shape[2])
        self._fill_indices(pg, off, slot, 0, S)
        self._scatter_packed(kq, vq, pg, off)

    def _alloc_pages(self, slot, n):
        if not self.pool.can_alloc(n):
            raise RuntimeError("serving: out of KV pages")
        if len(self._seq_pages[slot]) + n > self.pages_per_seq:
            raise RuntimeError("serving: sequence exceeds max_seq_len")
        pages = self.pool.alloc(n)
        self._seq_pages[slot].extend(pages)
        start = len(self._seq_pages[slot]) - n
        for i, pg in enumerate(pages):
            self.page_table[slot, start + i] = pg
        m = self.metrics
        if m is not None:
            m.on_page_alloc(n)
        return pages

    def _prefill_into(self, slot, req: Request):
        c = self.config
        feed = self._feed_ids(req)
        S = len(feed)
        self.prefill_tokens += S
        _tl_count(req, "prefill")
        bucket = self._bucket_for(S)
        ids = np.zeros((1, bucket), np.int64)
        ids[0, :S] = feed
        # `prefill`'s epilogue is already row-sparse (one final row)
        self.logit_rows += 1
        with record_span("serving.prefill"):
            logits, k_all, v_all = prefill(
                self.params, jnp.asarray(ids), jnp.asarray(S), c,
                use_pallas=self._use_pallas_prefill)
        self._scatter_prompt(slot, k_all, v_all, S)
        req.slot = slot
        req._admit_order = self._order
        self._order += 1
        self._attach(slot, req)
        self._index_slot(slot, req)
        if getattr(req, "_resume", False):
            req._resume = False  # next_token survives from before eviction
        else:
            self._seed_first_token(
                slot, req, self._fetch_results(logits).reshape(-1))

    def _preempt_one(self, exclude):
        """Evict the most-recently admitted active request (never
        `exclude`): pages return to the pool and the request re-enters
        the HEAD of the waiting queue. Under preempt_policy="offload"
        its KV pages are first copied to host memory (resume = scatter
        back, no recompute); under "recompute" resume re-prefills
        prompt + generated-so-far. Returns False when nothing can be
        evicted."""
        # mid-chunked-prefill slots ARE evictable: their chunk state
        # (_pf_feed/_pf_cursor) lives on the Request, so offload resumes
        # the feed exactly where it stopped (cursor == saved length) and
        # recompute re-feeds the prompt from the start
        victims = [s for s, r in enumerate(self._slots)
                   if r is not None and s != exclude]
        if not victims:
            return False
        s = max(victims, key=lambda v: self._slots[v]._admit_order)
        req = self._slots[s]
        if self.preempt_policy == "offload":
            n_pg = len(self._seq_pages[s])
            # gather at the FIXED pages_per_seq width (tail reads the
            # trash page, sliced off after the transfer): a per-count
            # gather shape would be a fresh XLA compile per eviction size
            pg = np.full((self.pages_per_seq,), self.num_pages - 1,
                         np.int32)
            pg[:n_pg] = self._seq_pages[s]
            payload = {
                "k": np.asarray(self.k_pool[:, :, pg])[:, :, :n_pg],
                "v": np.asarray(self.v_pool[:, :, pg])[:, :, :n_pg],
                "ks": None if self.k_scale is None else
                      np.asarray(self.k_scale[:, :, pg])[:, :, :n_pg],
                "vs": None if self.v_scale is None else
                      np.asarray(self.v_scale[:, :, pg])[:, :, :n_pg],
            }
            # the KV itself parks in the host tier's PINNED stash —
            # one host-RAM ledger with the spilled prefix pages (no
            # second ad-hoc store); the request carries only shape
            # metadata. Stored verbatim: a resume must be exact.
            self.host_tier.stash_put(id(req), payload, n_pg)
            _tl_mark(req, "spill")
            req._offload = {
                "len": int(self.lengths[s]),
                # actual page count, NOT ceil(len/page_size): a victim
                # evicted right after its boundary growth already holds
                # the next (still-empty) page
                "pages": n_pg,
            }
        req._resume = True
        req.slot = None
        _tl_mark(req, "preempted")
        self._waiting.insert(0, req)
        _flight.record(
            "engine.preempt", rid=str(req.rid),
            policy=self.preempt_policy, slot=s,
            tokens=len(req.output), pages=len(self._seq_pages[s]))
        flagged = getattr(req, "_handoff_export", False)
        self._release(s)
        if flagged:
            # a preempted export candidate stays one: re-arm the flag
            # _release just consumed so the re-admission still hands off
            req._handoff_export = True
            self._handoff_pending += 1
        self.preemptions += 1
        m = self.metrics
        if m is not None:
            m.on_preempt(self.preempt_policy)
        return True

    def _restore_into(self, slot, req: Request):
        """Swap-in: scatter a host-offloaded request's KV pages into
        fresh device pages. No prefill compute; the pending next_token
        survived eviction on the Request itself."""
        o = req._offload
        S = o["len"]
        n_pages = o["pages"]
        self._seq_pages[slot] = []
        pages = self._alloc_pages(slot, n_pages)
        p = self.host_tier.stash_take(id(req))
        self._scatter_host_kv(pages, p["k"], p["v"], p["ks"], p["vs"])
        self.lengths[slot] = S
        req._offload = None
        req._resume = False
        req.slot = slot
        req._admit_order = self._order
        self._order += 1
        self._attach(slot, req)
        self._stage_tokbuf(slot, req)

    def _scatter_host_kv(self, pages, k, v, ks, vs):
        """Scatter host-resident page KV (np, (L, KVH, n, page, D))
        into device `pages` — the single swap-in path shared by
        preemption restore and host-tier restore. Scatters at the
        fixed pages_per_seq width (tail -> trash page), mirroring the
        offload gather: one compile total, not one per page count."""
        n = len(pages)
        ppseq = self.pages_per_seq
        pg = np.full((ppseq,), self.num_pages - 1, np.int32)
        pg[:n] = pages

        def pad(a):
            out = np.zeros(a.shape[:2] + (ppseq,) + a.shape[3:], a.dtype)
            out[:, :, :n] = a
            return out
        self.k_pool = self.k_pool.at[:, :, pg].set(
            jnp.asarray(pad(k), self.k_pool.dtype))
        self.v_pool = self.v_pool.at[:, :, pg].set(
            jnp.asarray(pad(v), self.v_pool.dtype))
        if self.cache_quant:
            self.k_scale = self.k_scale.at[:, :, pg].set(
                jnp.asarray(pad(ks), jnp.float32))
            self.v_scale = self.v_scale.at[:, :, pg].set(
                jnp.asarray(pad(vs), jnp.float32))

    def _drop_offload(self, req):
        """Forget a waiting request's host-stashed KV (cancel/failure
        paths) — the tier ledger must not keep bytes for a request
        that will never resume."""
        if getattr(req, "_offload", None) is not None:
            self.host_tier.stash_discard(id(req))
        req._offload = None

    @staticmethod
    def _prefilling(req):
        """True while a chunked-prefill admission still has prompt
        tokens to feed."""
        feed = getattr(req, "_pf_feed", None)
        return feed is not None and req._pf_cursor < len(feed)

    def _seed_first_token(self, slot, req, row):
        """Sample/argmax the first generated token from the prefill's
        final-position logits `row` (np, (V,)) — single source for the
        monolithic, varlen-batch, and chunked prefill completions."""
        tok = req.pick(row) if req.temperature > 0.0 else int(np.argmax(row))
        req.next_token = tok
        req.output.append(tok)
        req.note_logprob(tok, row)
        self._note_emit(req, 1)
        if req.done:  # e.g. max_new_tokens == 1
            self.finished.append(req)
            self._note_finish(req)
            self._release(slot)
        elif self.tok_buf is not None:
            # the host-picked seed is the token position `lengths`
            # consumes next — poke it into the device token ring
            self.tok_buf = _tokbuf_poke(
                self.tok_buf, np.int32(slot),
                np.int32(int(self.lengths[slot])), np.int32(tok))

    # -- disaggregated prefill/decode handoff -----------------------------
    def _clear_handoff_flag(self, req):
        """Consume a request's export flag, keeping the fast-path
        pending counter honest. Safe to call on unflagged requests."""
        if getattr(req, "_handoff_export", False):
            req._handoff_export = False
            self._handoff_pending = max(0, self._handoff_pending - 1)

    def _harvest_handoffs(self):
        """Export-and-finish every live slot flagged for handoff whose
        prompt is fully prefilled and seeded. Runs at the top of each
        launch, BEFORE decode planning: a slot only becomes eligible
        the launch after its seeding finish, and that previous launch
        skipped it (next_token was still None), so no in-flight wave
        touches the slot — its KV is exactly prompt-complete and
        `lengths` was never advanced past the prompt."""
        if self._handoff_pending <= 0:
            return
        if self.host_tier is None:
            # no tier, no export path: flagged requests simply decode
            # locally to completion (flags clear at release)
            return
        for s in sorted(self._live):
            req = self._slots[s]
            if req is None or not getattr(req, "_handoff_export", False):
                continue
            if req.next_token is None or self._prefilling(req):
                continue  # prefill (or its seeding fetch) still pending
            self._export_handoff(s, req)
        # mirror immediately: if that was the last live slot the engine
        # idles, and no later on_step would carry the export deltas
        # (counters + duration) onto /metrics
        m = self.metrics
        if m is not None:
            m.on_handoff(self)

    def _export_handoff(self, s, req):
        """Ship slot `s`'s KV pages out as a KVHandoff and finish the
        request here with state "handoff" (the decode replica owns the
        rest of its life). The gather/fence/quantize runs on the tier's
        copy thread (`HostTier.export_pages`) — same explicit-fence
        discipline as a spill, nothing syncs the pump thread's device
        queue beyond the blocking wait itself. On ANY failure the slot
        is left exactly as it was and the request simply keeps decoding
        locally — degradation, never a drop."""
        t0 = time.perf_counter()
        self._clear_handoff_flag(req)
        n_pg = len(self._seq_pages[s])
        # fixed-width gather like _preempt_one: tail reads trash page,
        # sliced off host-side — one XLA gather shape for all exports
        pg = np.full((self.pages_per_seq,), self.num_pages - 1, np.int32)
        pg[:n_pg] = self._seq_pages[s]
        try:
            p = self.host_tier.export_pages(
                self.k_pool[:, :, pg], self.v_pool[:, :, pg],
                None if self.k_scale is None else self.k_scale[:, :, pg],
                None if self.v_scale is None else self.v_scale[:, :, pg],
                prequantized=self.cache_quant, rids=[str(req.rid)])
        except Exception as e:
            self.handoff_failures += 1
            _flight.record("handoff.fail", rid=str(req.rid),
                           trace_id=getattr(req, "_trace_id", None),
                           where="export", error=repr(e))
            return  # slot untouched -> local decode from here on
        _tl_mark(req, "handoff_export")
        tl = getattr(req, "_timeline", None)
        h = KVHandoff(
            rid=req.rid, prompt=req.prompt, output=req.output,
            next_token=int(req.next_token), length=int(self.lengths[s]),
            pages=n_pg,
            k=p["k"][:, :, :n_pg], v=p["v"][:, :, :n_pg],
            ks=None if p["ks"] is None else p["ks"][:, :, :n_pg],
            vs=None if p["vs"] is None else p["vs"][:, :, :n_pg],
            quantized=p["ks"] is not None,
            trace_id=getattr(req, "_trace_id", None),
            logprobs=req.logprobs, cached_tokens=req.cached_tokens,
            timeline=None if tl is None else tl.to_dict())
        req._handoff_done = h
        self.handoff_exports += 1
        self.handoff_bytes += h.nbytes
        self._handoff_times.append(time.perf_counter() - t0)
        _flight.record("handoff.export", rid=str(req.rid),
                       trace_id=h.trace_id, pages=n_pg, bytes=h.nbytes,
                       tokens=h.length)
        # finish WITHOUT _note_finish: the decode replica completes the
        # request; this replica's ledger records it as a handoff.
        self.finished.append(req)
        self._release(s)  # indexes the prefix first -> source keeps cache
        req.slot = None

    def _import_handoff(self, slot, req):
        """Decode-side scatter of a KVHandoff into fresh pages (the
        preemption swap-in path, `_scatter_host_kv`), adapting the wire
        encoding to this pool's dtype host-side. Returns True on
        success; on ANY failure the fresh pages are returned to the
        pool (crash_reset-grade release discipline) and the caller
        falls back to the recompute-resume prefill path — token-
        identical replay, never a dropped request."""
        h = req._kv_import
        t0 = time.perf_counter()
        self._seq_pages[slot] = []
        try:
            # fault point BEFORE the alloc: a raise here leaks nothing
            self._fire("handoff_import", rids=[str(req.rid)])
            pages = self._alloc_pages(slot, h.pages)
            try:
                k, v, ks, vs = h.k, h.v, h.ks, h.vs
                if ks is not None and not self.cache_quant:
                    k, v = _dequantize_host(k, ks), _dequantize_host(v, vs)
                    ks = vs = None
                elif ks is None and self.cache_quant:
                    k, ks = _quantize_host(k)
                    v, vs = _quantize_host(v)
                self._scatter_host_kv(pages, k, v, ks, vs)
            except BaseException:
                self.pool.decref(pages)
                self._seq_pages[slot] = []
                self.page_table[slot, :] = self.num_pages - 1
                raise
        except Exception as e:
            self.handoff_failures += 1
            _flight.record("handoff.fail", rid=str(req.rid),
                           trace_id=h.trace_id, where="import",
                           error=repr(e))
            req._kv_import = None
            req._resume = True  # recompute path: prompt + output[:-1]
            return False
        self.lengths[slot] = h.length
        _tl_mark(req, "handoff_import")
        req._kv_import = None
        req._resume = False
        req.slot = slot
        req._admit_order = self._order
        self._order += 1
        self._attach(slot, req)
        self._stage_tokbuf(slot, req)
        self._index_slot(slot, req)
        self.handoff_imports += 1
        self.handoff_bytes += h.nbytes
        self._handoff_times.append(time.perf_counter() - t0)
        _flight.record("handoff.import", rid=str(req.rid),
                       trace_id=h.trace_id, pages=h.pages, bytes=h.nbytes,
                       tokens=h.length)
        return True

    # -- decode loop ------------------------------------------------------
    def step(self):
        """One decode step for all active slots; returns #active.
        Synchronous driver: launch + consume in one call. The pipelined
        pump calls `step_launch`/`step_finish` itself so the consume of
        step N overlaps the device executing step N+1."""
        self._sweep_cancelled()
        self._harvest_handoffs()
        self._admit()
        if self.spec_decode > 1:
            return self._spec_step()
        t = self.step_launch(_admitted=True)
        return 0 if t is None else self.step_finish(t)

    def _note_launch_gap(self, depth):
        """Host-gap + pipeline-depth telemetry, taken at the instant a
        decode/verify program is about to dispatch: the wall time since
        the previous dispatch RETURNED is exactly how long the device
        had no step-loop program queued behind the running one."""
        now = time.perf_counter()
        m = self.metrics
        if self._t_launch_end is not None:
            self.last_host_gap_s = now - self._t_launch_end
            if m is not None:
                m.observe_host_gap(self.last_host_gap_s)
        self.pipeline_depth = depth
        if m is not None:
            m.set_pipeline_depth(depth)

    def step_launch(self, carry=None, _admitted=False):
        """Admission + page growth + ONE decode_step dispatch, with NO
        device read: returns a StepTicket whose result record is still
        on device (None when nothing runs). `carry` is the previous,
        still-unconsumed ticket — continuing slots take their input
        token from its device record (`carry_mask` inside the step), so
        the host launches step N+1 knowing nothing about step N.

        A carried slot that will exhaust max_new_tokens in the
        in-flight step is NOT launched (its finish is host-predictable);
        an eos finish is not, so such a slot runs one discarded zombie
        step and `step_finish` rolls its length back. Raises
        PipelineStall instead of preempting while carrying — the
        victim's pending token is still in flight."""
        if self.ragged:
            return self._ragged_launch(carry=carry, _admitted=_admitted)
        if not _admitted:
            self._sweep_cancelled()
            self._harvest_handoffs()
            self._admit()
        # page-growth pass with preemption, over OCCUPIED slots only: a
        # slot about to cross a page boundary must get a page; when the
        # (oversubscribed) pool is dry, evict the most recent admission
        # rather than dying deep in the allocator
        for s in sorted(self._live):
            cur = int(self.lengths[s])
            if cur % self.page_size == 0 and cur > 0 and \
                    len(self._seq_pages[s]) * self.page_size <= cur:
                while not self.pool.can_alloc(1):
                    if carry is not None:
                        raise PipelineStall(
                            "page growth needs a preemption victim "
                            "with a step in flight")
                    if not self._preempt_one(exclude=s):
                        raise RuntimeError(
                            "serving: KV page pool exhausted with a "
                            "single active sequence — num_pages is too "
                            "small for max_seq_len")
                self._alloc_pages(s, 1)
        if not self._live:
            self._t_launch_end = None
            return None
        B = self.max_seqs
        tokens = np.zeros((B,), np.int32)
        carry_mask = np.zeros((B,), bool)
        temps = np.zeros((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        keys = np.zeros((B, 2), np.uint32)
        eos = np.full((B,), -1, np.int32)
        remaining = np.ones((B,), np.int32)
        launch, reqs = [], {}
        for s in sorted(self._live):
            req = self._slots[s]
            carried = carry is not None and carry.reqs.get(s) is req
            left = req.max_new_tokens - len(req.output) \
                - (1 if carried else 0)
            if left <= 0:
                continue  # the in-flight step emits its last token
            launch.append(s)
            reqs[s] = req
            if carried:
                carry_mask[s] = True
            else:
                tokens[s] = req.next_token
            temps[s] = req.temperature
            top_ks[s] = req.top_k
            top_ps[s] = req.top_p
            if req._base_key is not None:
                keys[s] = req._base_key
            if req.eos_id is not None:
                eos[s] = int(req.eos_id)
            remaining[s] = left
        if not launch:
            return None  # every occupied slot is finishing in flight
        active = np.zeros((B,), bool)
        active[launch] = True
        self.lengths = np.where(active, self.lengths + 1, self.lengths)
        sample = {"temp": jnp.asarray(temps),
                  "top_k": jnp.asarray(top_ks),
                  "top_p": jnp.asarray(top_ps),
                  "key": jnp.asarray(keys),
                  "eos": jnp.asarray(eos),
                  "remaining": jnp.asarray(remaining)}
        # always pass the carry operands (zeros when none): an arity
        # flip between the first pipelined launch and the rest would be
        # a second trace signature for no reason
        c_tok = carry.next_tok if carry is not None \
            else jnp.zeros((B,), jnp.int32)
        # fault point: one hit per decode dispatch, with the launched
        # request ids so rid-scoped rules can model a poison request
        self._fire("step_launch", rids=[str(reqs[s].rid) for s in launch])
        self._note_launch_gap(1 if carry is not None else 0)
        # bucketed decode is one row per slot already — no rows to skip
        self.logit_rows += B
        # page_table/lengths go to the device as SNAPSHOTS (.copy(), a
        # few hundred bytes): jnp.asarray may zero-copy a numpy buffer
        # on CPU, and the host mutates both tables in place (release /
        # admission) as soon as the results land — while the same
        # step's K/V scatter thunks can still be reading them under
        # XLA's async thunk runtime. Observed as a rare (<1%)
        # final-token corruption under concurrent serving load.
        with record_span("serving.decode_step"):
            (self.k_pool, self.v_pool, self.k_scale, self.v_scale,
             _logits, rec) = decode_step(
                self.params, self.k_pool, self.v_pool,
                jnp.asarray(self.page_table.copy()),
                jnp.asarray(self.lengths.copy()),
                jnp.asarray(tokens), jnp.asarray(active),
                self.config, self.page_size, use_pallas=self._use_pallas,
                interpret=self._interpret, k_scale=self.k_scale,
                v_scale=self.v_scale, mesh=self._mesh,
                sample=sample, carry_tok=c_tok,
                carry_mask=jnp.asarray(carry_mask))
        self._t_launch_end = time.perf_counter()
        self.device_steps += 1
        return StepTicket(launch, reqs, rec[0], rec[1], rec[2])

    def step_finish(self, ticket, inflight=None):
        """Consume a launched step: ONE batched transfer of a few ints
        per slot (the device already sampled and evaluated the stop
        conditions), then the host bookkeeping. `inflight` is the
        ticket launched AFTER this one (pipelined pump): a slot that
        finishes here already ran one step past its end in `inflight`,
        so its entry there is zombied and its length rolled back —
        release/indexing then see exactly the synchronous loop's
        state."""
        if self.ragged:
            return self._ragged_finish(ticket, inflight=inflight)
        self._fire("step_finish",
                   rids=[str(r.rid) for r in ticket.reqs.values()
                         if r is not None])
        nxt, done, lp = self._fetch_results(
            (ticket.next_tok, ticket.done, ticket.logprob))
        for s in ticket.slots:
            req = ticket.reqs.get(s)
            if req is None or self._slots[s] is not req:
                continue  # zombie: slot released/reused since launch
            tok = int(nxt[s])
            req.output.append(tok)
            req.next_token = tok
            if req.want_logprobs:
                req.logprobs.append(float(lp[s]))
            self._note_emit(req, 1)
            if bool(done[s]):
                self.finished.append(req)
                self._note_finish(req)
                if inflight is not None and inflight.reqs.get(s) is req:
                    inflight.reqs[s] = None
                    self.lengths[s] -= 1
                self._release(s)
        self._note_step(len(ticket.slots))
        return len(ticket.slots)

    def _ragged_launch(self, carry=None, _admitted=False):
        """Ragged twin of `step_launch`: ONE `unified_step` dispatch
        serving every live slot — single-token decode rows AND
        chunked-prefill feeds — as rows of a flat (slot, pos, token)
        descriptor buffer. No padding buckets: the buffer holds exactly
        the tokens fed (unused tail rows carry pos=-1 and the kernel
        skips them), so the mix changing between waves never changes
        the trace signature. State (lengths, prefill cursors) advances
        AT LAUNCH so a pipelined launch N+1 plans against consistent
        state; `step_finish`-side rollback (eos zombie) is identical to
        the bucketed path. A slot whose prefill completed in the
        in-flight wave is unseeded (next_token None) and sits out one
        wave — its first token is picked host-side at finish, the PR 8
        seeding convention, so outputs stay token-identical."""
        if not _admitted:
            self._sweep_cancelled()
            self._harvest_handoffs()
            self._admit()
        # decode-boundary page growth, bucketed logic verbatim (mid-
        # prefill slots grow against their own chunk below)
        for s in sorted(self._live):
            if self._prefilling(self._slots[s]):
                continue
            cur = int(self.lengths[s])
            if cur % self.page_size == 0 and cur > 0 and \
                    len(self._seq_pages[s]) * self.page_size <= cur:
                while not self.pool.can_alloc(1):
                    if carry is not None:
                        raise PipelineStall(
                            "page growth needs a preemption victim "
                            "with a step in flight")
                    if not self._preempt_one(exclude=s):
                        raise RuntimeError(
                            "serving: KV page pool exhausted with a "
                            "single active sequence — num_pages is too "
                            "small for max_seq_len")
                self._alloc_pages(s, 1)
        if not self._live:
            self._t_launch_end = None
            return None
        # plan decode rows (no state mutation yet — preemption during
        # the feed-growth pass below may still evict a planned slot)
        decode_plan = []
        for s in sorted(self._live):
            req = self._slots[s]
            if self._prefilling(req):
                continue
            if req.next_token is None:
                continue  # seeding rides the in-flight wave's finish
            carried = carry is not None and carry.reqs.get(s) is req
            left = req.max_new_tokens - len(req.output) \
                - (1 if carried else 0)
            if left <= 0:
                continue  # the in-flight step emits its last token
            decode_plan.append((s, req, carried, left))
        # plan prefill feeds into the remaining buffer rows, growing
        # pages for every real chunk position now
        room = self.ragged_buf - len(decode_plan)
        prefill_plan = []
        for s in sorted(self._live):
            req = self._slots[s]
            if not self._prefilling(req):
                continue
            n = min(len(req._pf_feed) - req._pf_cursor, room)
            if n <= 0:
                continue  # buffer full this wave; slot feeds next wave
            need = -(-(int(self.lengths[s]) + n) // self.page_size)
            while len(self._seq_pages[s]) < need:
                while not self.pool.can_alloc(1):
                    if carry is not None:
                        raise PipelineStall(
                            "prefill growth needs a preemption victim "
                            "with a step in flight")
                    if not self._preempt_one(exclude=s):
                        raise RuntimeError(
                            "serving: KV page pool exhausted with a "
                            "single active sequence — num_pages is too "
                            "small for max_seq_len")
                self._alloc_pages(s, 1)
            prefill_plan.append((s, req, n))
            room -= n
        # a preemption above may have evicted a planned slot
        decode_plan = [p for p in decode_plan if self._slots[p[0]] is p[1]]
        prefill_plan = [p for p in prefill_plan
                        if self._slots[p[0]] is p[1]]
        if not decode_plan and not prefill_plan:
            return None  # every occupied slot is finishing/seeding
        T = self.ragged_buf
        B = self.max_seqs
        tokens = np.zeros((T,), np.int32)
        tok_slot = np.zeros((T,), np.int32)
        tok_pos = np.full((T,), -1, np.int32)
        carry_mask = np.zeros((T,), bool)
        carry_gather = np.zeros((T,), np.int32)
        temps = np.zeros((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        keys = np.zeros((B, 2), np.uint32)
        eos = np.full((B,), -1, np.int32)
        remaining = np.ones((B,), np.int32)
        flat, reqs = {}, {}
        row = 0
        for s, req, carried, left in decode_plan:
            tok_slot[row] = s
            tok_pos[row] = int(self.lengths[s])
            if self.tok_buf is None:
                if carried:
                    carry_mask[row] = True
                    carry_gather[row] = carry.flat[s]
                else:
                    tokens[row] = req.next_token
            # tokbuf engines ship NO token values: the row's token is
            # device-resident (staged at admission, scattered by the
            # previous wave, or poked at seeding) — which also subsumes
            # the pipelined carry gather
            temps[s] = req.temperature
            top_ks[s] = req.top_k
            top_ps[s] = req.top_p
            if req._base_key is not None:
                keys[s] = req._base_key
            if req.eos_id is not None:
                eos[s] = int(req.eos_id)
            remaining[s] = left
            self.lengths[s] += 1
            flat[s] = row
            reqs[s] = req
            row += 1
        seeds, seed_flat = [], []
        for s, req, n in prefill_plan:
            feed, cur = req._pf_feed, req._pf_cursor
            base = int(self.lengths[s])
            if self.tok_buf is None:
                tokens[row:row + n] = feed[cur:cur + n]
            tok_slot[row:row + n] = s
            tok_pos[row:row + n] = base + np.arange(n, dtype=np.int32)
            req._pf_cursor += n
            _tl_count(req, "prefill")
            self.lengths[s] += n
            self.prefill_tokens += n
            if req._pf_cursor >= len(feed):
                # feed complete: index the slot's full pages NOW (the
                # bucketed prefill paths index right after dispatch),
                # so a live decoding slot's prefix is shareable by the
                # very next admission
                self._index_slot(s, req)
                if req._pf_sample:
                    # last chunk: its final row's logits seed the first
                    # generated token host-side at finish
                    seeds.append((s, req))
                    seed_flat.append(row + n - 1)
            row += n
        self.ragged_tokens += row
        sample = {"temp": jnp.asarray(temps),
                  "top_k": jnp.asarray(top_ks),
                  "top_p": jnp.asarray(top_ps),
                  "key": jnp.asarray(keys),
                  "eos": jnp.asarray(eos),
                  "remaining": jnp.asarray(remaining)}
        need_rows = None
        n_decode = len(decode_plan)
        if self.lean:
            # need-row descriptor: decode rows sit at buffer rows
            # 0..n_decode-1 (so flat[s] doubles as the need index) and
            # completed-prefill seed rows follow; -1 pads the fixed
            # shape, so the mix changing never retraces
            need = np.full((self.need_buf,), -1, np.int32)
            need[:n_decode] = np.arange(n_decode, dtype=np.int32)
            need[n_decode:n_decode + len(seed_flat)] = seed_flat
            need_rows = jnp.asarray(need)
            self.logit_rows += self.need_buf
            self.logit_rows_skipped += T - self.need_buf
        else:
            self.logit_rows += T
        c_tok = carry.next_tok if carry is not None \
            else jnp.zeros((self.need_buf if self.lean else T,),
                           jnp.int32)
        self._fire("step_launch",
                   rids=[str(p[1].rid) for p in decode_plan] +
                        [str(p[1].rid) for p in prefill_plan])
        self._note_launch_gap(1 if carry is not None else 0)
        with record_span("serving.unified_step"):
            if self.tok_buf is not None:
                # device token ring: no carry operands (the ring's
                # in-jit scatter/gather IS the carry) — decode rows
                # write their sampled token for the next wave to read
                bw = np.zeros((self.need_buf if self.lean else T,),
                              bool)
                bw[:n_decode] = True
                (self.k_pool, self.v_pool, self.k_scale, self.v_scale,
                 logits, rec, self.tok_buf) = unified_step(
                    self.params, self.k_pool, self.v_pool,
                    jnp.asarray(self.page_table.copy()),
                    jnp.asarray(tokens), jnp.asarray(tok_slot),
                    jnp.asarray(tok_pos), self.config, self.page_size,
                    use_pallas=self._use_pallas,
                    interpret=self._interpret,
                    k_scale=self.k_scale, v_scale=self.v_scale,
                    sample=sample, need_rows=need_rows,
                    block_q=self._block_q,
                    block_pages=self._block_pages,
                    tok_buf=self.tok_buf, buf_write=jnp.asarray(bw))
            else:
                (self.k_pool, self.v_pool, self.k_scale, self.v_scale,
                 logits, rec) = unified_step(
                    self.params, self.k_pool, self.v_pool,
                    jnp.asarray(self.page_table.copy()),
                    jnp.asarray(tokens), jnp.asarray(tok_slot),
                    jnp.asarray(tok_pos), self.config, self.page_size,
                    use_pallas=self._use_pallas,
                    interpret=self._interpret,
                    k_scale=self.k_scale, v_scale=self.v_scale,
                    sample=sample, carry_tok=c_tok,
                    carry_gather=jnp.asarray(carry_gather),
                    carry_mask=jnp.asarray(carry_mask),
                    need_rows=need_rows, block_q=self._block_q,
                    block_pages=self._block_pages)
        if not seeds:
            seed_rows = None
        elif need_rows is not None:
            # lean: seed rows were gathered into need positions
            # n_decode.. — the (T, vocab) buffer never existed
            seed_rows = logits[jnp.arange(
                n_decode, n_decode + len(seeds), dtype=jnp.int32)]
        else:
            seed_rows = logits[jnp.asarray(seed_flat, jnp.int32)]
        self._t_launch_end = time.perf_counter()
        self.device_steps += 1
        return RaggedTicket(reqs, flat, rec[0], rec[1], rec[2], seeds,
                            seed_rows,
                            sorted([p[0] for p in decode_plan] +
                                   [p[0] for p in prefill_plan]))

    def _ragged_finish(self, ticket, inflight=None):
        """Ragged twin of `step_finish`: ONE batched transfer (decode
        records + completed-prefill logits rows), then host bookkeeping.
        Seeds land first — the bucketed path seeds at admission, before
        any decode consume — then decode rows in slot order with the
        identical zombie / eos-rollback contract."""
        self._fire("step_finish",
                   rids=[str(r.rid) for r in ticket.reqs.values()
                         if r is not None] +
                        [str(r.rid) for _, r in ticket.seeds])
        nxt, done, lp, seed_rows = self._fetch_results(
            (ticket.next_tok, ticket.done, ticket.logprob,
             ticket.seed_rows))
        if seed_rows is not None:
            for (s, req), rowv in zip(ticket.seeds, seed_rows):
                if self._slots[s] is not req:
                    continue  # zombie: slot released/reused since launch
                self._seed_first_token(s, req, rowv)
        for s in sorted(ticket.flat):
            req = ticket.reqs.get(s)
            if req is None or self._slots[s] is not req:
                continue  # zombie: slot released/reused since launch
            i = ticket.flat[s]
            tok = int(nxt[i])
            req.output.append(tok)
            req.next_token = tok
            if req.want_logprobs:
                req.logprobs.append(float(lp[i]))
            self._note_emit(req, 1)
            if bool(done[i]):
                self.finished.append(req)
                self._note_finish(req)
                if inflight is not None and inflight.reqs.get(s) is req:
                    inflight.reqs[s] = None
                    self.lengths[s] -= 1
                self._release(s)
        self._note_step(len(ticket.slots))
        return len(ticket.slots)

    def _spec_step(self):
        """One speculative verify step: drafts up to G-1 tokens per
        greedy slot by prompt lookup, verifies the whole chunk in one
        forward, emits the accepted prefix + one model token. Exactly
        reproduces plain greedy decode (the model token at the first
        draft divergence is the token plain decode would have picked)."""
        G = self.spec_decode
        active_slots = sorted(self._live)
        if not active_slots:
            self._t_launch_end = None
            return 0
        tokens = np.zeros((self.max_seqs, G), np.int64)
        n_tok = np.ones((self.max_seqs,), np.int32)
        active = np.zeros((self.max_seqs,), bool)
        for s in active_slots:
            req = self._slots[s]
            active[s] = True
            if self._prefilling(req):
                # chunked prefill: the chunk is the next G prompt tokens
                feed, cur = req._pf_feed, req._pf_cursor
                n = min(G, len(feed) - cur)
                tokens[s, :n] = feed[cur:cur + n]
                n_tok[s] = n
                self.prefill_tokens += n
                continue
            tokens[s, 0] = req.next_token
            cur = int(self.lengths[s])
            room = self.max_seq_len - cur - 1
            budget = min(G - 1, room,
                         req.max_new_tokens - len(req.output))
            if budget > 0 and (req.temperature == 0.0 or self.spec_sample):
                # context = everything decided so far incl. the pending
                # next_token (it's the tail the n-gram keys off)
                ctx = req.prompt + req.output
                draft = prompt_lookup_draft(ctx, budget, self.spec_ngram)
                for j, t in enumerate(draft):
                    tokens[s, 1 + j] = t
                n_tok[s] = 1 + len(draft)
                self.spec_drafted += len(draft)
        # page growth: every REAL chunk position needs its page now
        for s in active_slots:
            if self._slots[s] is None:
                continue   # evicted by a preemption for an earlier slot
            need = -(-(int(self.lengths[s]) + int(n_tok[s]))
                     // self.page_size)
            while len(self._seq_pages[s]) < need:
                while not self.pool.can_alloc(1):
                    if not self._preempt_one(exclude=s):
                        raise RuntimeError(
                            "serving: KV page pool exhausted with a "
                            "single active sequence — num_pages is too "
                            "small for max_seq_len")
                self._alloc_pages(s, 1)
        active_slots = sorted(self._live)
        for s in range(self.max_seqs):
            if s not in active_slots:
                active[s] = False
        if not active_slots:
            return 0
        temps = np.zeros((self.max_seqs,), np.float32)
        top_ks = np.zeros((self.max_seqs,), np.int32)
        top_ps = np.ones((self.max_seqs,), np.float32)
        keys = np.zeros((self.max_seqs, 2), np.uint32)
        for s in active_slots:
            req = self._slots[s]
            if self._prefilling(req):
                continue  # chunk feed: nothing sampled on device
            temps[s] = req.temperature
            top_ks[s] = req.top_k
            top_ps[s] = req.top_p
            if req._base_key is not None:
                keys[s] = req._base_key
        sample = {"temp": jnp.asarray(temps),
                  "top_k": jnp.asarray(top_ks),
                  "top_p": jnp.asarray(top_ps),
                  "key": jnp.asarray(keys)}
        # one rows dict for the SAMPLING requests only: rejection
        # sampling (speculative_sample) needs the full filtered
        # distribution, so those rows still come to host. Greedy slots
        # — logprobs included — ride the device verify record: the
        # argmax grid and its raw-model logprobs are (B, G) ints and
        # floats, never a vocab row. Everything the host needs this
        # step — grids, sampling rows, and the final-chunk row that
        # seeds a finishing prefill — rides the engine's ONE sanctioned
        # batched read (`_fetch_results`).
        need_rows = [s for s in active_slots
                     if self._slots[s].temperature > 0.0
                     and int(n_tok[s]) > 1
                     and not self._prefilling(self._slots[s])]
        seed_slots = [s for s in active_slots
                      if self._prefilling(self._slots[s])
                      and self._slots[s]._pf_cursor + int(n_tok[s])
                      >= len(self._slots[s]._pf_feed)
                      and self._slots[s]._pf_sample]
        # same fault point as step_launch: one hit per device step,
        # whichever dispatch the engine mode uses
        self._fire("step_launch",
                   rids=[str(self._slots[s].rid) for s in active_slots])
        self._note_launch_gap(0)
        if self.ragged:
            # ragged dispatch: each slot's verify chunk occupies
            # n_tok[s] consecutive rows of the flat buffer; row
            # base[s]+g is sampled with fold lengths+g+1 — the bucketed
            # grid's exact (seed, position) key — so the shared
            # acceptance loop below sees token-identical grids
            base = {}
            row = 0
            for s in active_slots:
                base[s] = row
                row += int(n_tok[s])
            T = self.ragged_buf
            ftok = np.zeros((T,), np.int32)
            fslot = np.zeros((T,), np.int32)
            fpos = np.full((T,), -1, np.int32)
            for s in active_slots:
                n = int(n_tok[s])
                b = base[s]
                ftok[b:b + n] = tokens[s, :n]
                fslot[b:b + n] = s
                fpos[b:b + n] = int(self.lengths[s]) + \
                    np.arange(n, dtype=np.int32)
            self.ragged_tokens += row
            need_desc = cand = None
            if self.lean:
                # lean epilogue: the wave's rows ARE the needed rows
                # (every chunk position feeds the verify record), so
                # the descriptor is the identity over the packed rows
                # — the unembed runs at need_buf rows, never T. cand
                # carries each row's FOLLOWING draft token so the
                # rejection sampler's accept tests ride the record.
                need = np.full((self.need_buf,), -1, np.int32)
                need[:row] = np.arange(row, dtype=np.int32)
                need_desc = jnp.asarray(need)
                cand_np = np.zeros((self.need_buf,), np.int32)
                for s in active_slots:
                    n = int(n_tok[s])
                    if n > 1:
                        cand_np[base[s]:base[s] + n - 1] = tokens[s, 1:n]
                cand = jnp.asarray(cand_np)
                self.logit_rows += self.need_buf
                self.logit_rows_skipped += T - self.need_buf
            else:
                self.logit_rows += T
            c_shape = self.need_buf if self.lean else T
            with record_span("serving.unified_step"):
                (self.k_pool, self.v_pool, self.k_scale, self.v_scale,
                 logits, rec) = unified_step(
                    self.params, self.k_pool, self.v_pool,
                    jnp.asarray(self.page_table.copy()),
                    jnp.asarray(ftok), jnp.asarray(fslot),
                    jnp.asarray(fpos), self.config, self.page_size,
                    use_pallas=self._use_pallas,
                    interpret=self._interpret, k_scale=self.k_scale,
                    v_scale=self.v_scale, sample=sample,
                    carry_tok=jnp.zeros((c_shape,), jnp.int32),
                    carry_gather=jnp.zeros((T,), jnp.int32),
                    carry_mask=jnp.zeros((T,), bool),
                    need_rows=need_desc, cand_tok=cand,
                    block_q=self._block_q,
                    block_pages=self._block_pages)
            self._t_launch_end = time.perf_counter()
            self.device_steps += 1
            self._fire("step_finish",
                       rids=[str(self._slots[s].rid)
                             for s in active_slots])
            need_idx = np.concatenate(
                [np.arange(base[s], base[s] + int(n_tok[s]),
                           dtype=np.int32) for s in need_rows]) \
                if need_rows and not self.lean else None
            seed_idx = [base[s] + int(n_tok[s]) - 1 for s in seed_slots]
            # lean narrowing: the sampling slots' pull is rec[3]'s
            # candidate probabilities (a float per draft) instead of
            # vocab rows; divergence/final rows come lazily through
            # `_spec_row_dist`
            tok_f, lp_f, row_f, cand_f, seed_vals = self._fetch_results(
                (rec[0], rec[2],                          # (T|N,) each
                 logits[jnp.asarray(need_idx)]
                 if need_idx is not None else None,
                 rec[3] if self.lean else None,
                 logits[jnp.asarray(seed_idx, jnp.int32)]
                 if seed_slots else None))
            grid = np.zeros((self.max_seqs, G), np.int64)
            lp_grid = np.zeros((self.max_seqs, G), np.float32)
            for s in active_slots:
                n = int(n_tok[s])
                grid[s, :n] = tok_f[base[s]:base[s] + n]
                lp_grid[s, :n] = lp_f[base[s]:base[s] + n]
            rows_by_slot, cand_by_slot = {}, {}
            if row_f is not None:
                off = 0
                for s in need_rows:
                    n = int(n_tok[s])
                    rows_by_slot[s] = row_f[off:off + n]
                    off += n
            if cand_f is not None:
                for s in need_rows:
                    n = int(n_tok[s])
                    cand_by_slot[s] = cand_f[base[s]:base[s] + n - 1]
            flat_logits = logits
            row_of = {s: base[s] for s in active_slots}
            seed_rows = {} if seed_vals is None else \
                dict(zip(seed_slots, seed_vals))
        else:
            cand = None
            if self.lean and need_rows:
                # bucketed narrowing: the verify grid's record carries
                # candidate probabilities, so sampling slots pull
                # (B, G) floats instead of (n, V) vocab rows
                cand_np = np.zeros((self.max_seqs, G), np.int32)
                for s in need_rows:
                    n = int(n_tok[s])
                    cand_np[s, :n - 1] = tokens[s, 1:n]
                cand = jnp.asarray(cand_np)
            self.logit_rows += self.max_seqs * G
            with record_span("serving.verify_step"):
                (self.k_pool, self.v_pool, self.k_scale, self.v_scale,
                 logits, rec) = verify_step(
                    self.params, self.k_pool, self.v_pool,
                    jnp.asarray(self.page_table.copy()),
                    jnp.asarray(self.lengths.copy()),
                    jnp.asarray(tokens), jnp.asarray(n_tok),
                    jnp.asarray(active), self.config, self.page_size,
                    use_pallas=self._use_pallas,
                    interpret=self._interpret,
                    k_scale=self.k_scale, v_scale=self.v_scale,
                    mesh=self._mesh, sample=sample, cand_tok=cand)
            grid_dev, lp_dev = rec[0], rec[1]
            self._t_launch_end = time.perf_counter()
            self.device_steps += 1
            self._fire("step_finish",
                       rids=[str(self._slots[s].rid)
                             for s in active_slots])
            grid, lp_grid, row_vals, cand_vals, seed_vals = \
                self._fetch_results(
                    (grid_dev, lp_dev,                    # (B, G) each
                     logits[jnp.asarray(need_rows, jnp.int32)]
                     if need_rows and cand is None else None,
                     rec[2] if cand is not None else None,
                     logits[jnp.asarray(seed_slots, jnp.int32),
                            jnp.asarray([int(n_tok[s]) - 1
                                         for s in seed_slots], jnp.int32)]
                     if seed_slots else None))
            rows_by_slot = {} if row_vals is None else \
                {s: row_vals[i][:int(n_tok[s])]
                 for i, s in enumerate(need_rows)}
            cand_by_slot = {} if cand_vals is None else \
                {s: cand_vals[s, :int(n_tok[s]) - 1] for s in need_rows}
            V = logits.shape[-1]
            flat_logits = logits.reshape(-1, V)
            row_of = {s: s * G for s in active_slots}
            seed_rows = {} if seed_vals is None else \
                dict(zip(seed_slots, seed_vals))
        for s in active_slots:
            req = self._slots[s]
            n = int(n_tok[s])
            if self._prefilling(req):
                # chunk fed; emit nothing until the prompt is complete,
                # then the final position's logits seed generation
                req._pf_cursor += n
                _tl_count(req, "prefill")
                self.lengths[s] += n
                if req._pf_cursor >= len(req._pf_feed) and req._pf_sample:
                    self._seed_first_token(s, req, seed_rows[s])
                continue
            rows = rows_by_slot.get(s)
            if req.temperature > 0.0 and n > 1:
                # speculative sampling: distributionally exact; rows
                # filter lazily (rejection at g touches g+1 rows only).
                # Lean engines accept against the record's candidate
                # probabilities and materialize a distribution row
                # (device-filtered, `_spec_row_dist`) only on
                # divergence or the final draw.
                if s in cand_by_slot:
                    outs, a = speculative_sample(
                        lambda g: self._spec_row_dist(
                            flat_logits, row_of[s] + g, req),
                        tokens[s, 1:n], req.rng,
                        cand_probs=cand_by_slot[s])
                else:
                    outs, a = speculative_sample(
                        lambda g: filtered_probs_np(
                            rows[g], req.temperature,
                            req.top_k, req.top_p),
                        tokens[s, 1:n], req.rng)
            elif req.temperature > 0.0:
                # un-drafted sampled slot: the device already drew the
                # token with the SAME (seed, position) key the plain
                # decode path uses — cross-mode seeded parity for free
                outs, a = [int(grid[s, 0])], 0
            else:
                outs = [int(t) for t in grid[s, :n]]
                # accept drafts while they match the model's own choices
                a = 0
                while a < n - 1 and tokens[s, a + 1] == outs[a]:
                    a += 1
                outs = outs[:a + 1]
            self.spec_accepted += a
            emitted = 0
            for j, tok in enumerate(outs):
                req.output.append(tok)
                req.next_token = tok
                if req.want_logprobs:
                    if rows is not None:
                        req.note_logprob(tok, rows[j])
                    elif s in cand_by_slot:
                        # lean sampled slot: pull THIS emission's raw
                        # row (logprobs opt-in pays per-token, the
                        # default path stays narrow)
                        req.note_logprob(tok, self._fetch_results(
                            flat_logits[row_of[s] + j]))
                    else:
                        # greedy: emitted token j IS the grid token at
                        # j, whose raw-model logprob came on device
                        req.logprobs.append(float(lp_grid[s, j]))
                emitted += 1
                if req.done:
                    break
            # cache retains chunk tokens 0..emitted-1 (the pending token
            # + the drafts CONSUMED to produce the emissions)
            self.lengths[s] += emitted
            self._note_emit(req, emitted)
            if req.done:
                self.finished.append(req)
                self._note_finish(req)
                self._release(s)
        self._note_step(len(active_slots))
        return len(active_slots)

    def _release(self, slot):
        req = self._slots[slot]
        if req is not None:
            self._clear_handoff_flag(req)
            # a finished/cancelled/preempted slot's KV is valid up to
            # `lengths` — index its full pages so later admissions
            # sharing the prefix skip their prefill
            self._index_slot(slot, req)
        # decref tail-first: deepest blocks park least-recently-used,
        # so eviction reclaims children before the prefixes they need
        self.pool.decref(reversed(self._seq_pages[slot]))
        self._seq_pages[slot] = []
        self.lengths[slot] = 0
        # re-point the freed row at the trash page: stale entries keep
        # aliasing pages the pool may re-hand to other slots
        self.page_table[slot, :] = self.num_pages - 1
        self._slots[slot] = None
        self._live.discard(slot)

    # -- prefix KV cache (serving/kvcache.py + serving/kvtier.py) ---------
    def _cache_acquire(self, feed, req=None):
        """Longest-prefix match for an admission candidate; matched
        pages are ref-counted immediately, so nothing later in this
        admission wave can evict them. Lookup falls through device ->
        host: where the device match ends, the host tier's index takes
        over and hits are restored into fresh device pages. Returns
        (pages, cached_tokens)."""
        pc = self.prefix_cache
        if pc is None:
            return [], 0
        pages, cached = pc.match(feed)
        if pages:
            self.pool.incref(pages)
        if self.host_tier.enabled:
            try:
                pages, cached = self._tier_restore(feed, pages, cached,
                                                   req)
            except BaseException:
                # a failed restore must give back the device-matched
                # refs NOW: the caller never sees them (req._kv_match
                # is only assigned on return), so crash recovery could
                # not find the leak
                if pages:
                    self.pool.decref(pages)
                raise
        return pages, cached

    def _tier_restore(self, feed, pages, cached, req):
        """Second lookup level: continue the prefix walk into the host
        tier and swap hits back in through the preemption restore
        machinery (`_scatter_host_kv`), re-indexing them in the device
        cache so this request — and every later one — maps them like
        ordinary cached pages. Restored pages arrive refcount-1 from
        alloc, matching the incref the device match took on its own
        pages, so `_cache_unacquire` treats both uniformly."""
        tier = self.host_tier
        blocks = tier.match(feed, cached)
        room = min(self.pages_per_seq - len(pages),
                   self.pool.available())
        n = min(len(blocks), max(room, 0))
        if n == 0:
            tier.note_lookup(0)
            return pages, cached
        blocks = blocks[:n]
        # fault point BEFORE the alloc: a raise here leaks nothing (the
        # device-matched incref is dropped by recovery's unacquire)
        self._fire("tier_restore",
                   rids=None if req is None else [str(req.rid)])
        # alloc may evict — and spill — OTHER parked pages; this
        # candidate's device-matched prefix is already increfed, so
        # the restore can never cannibalize its own chain
        new_pages = self.pool.alloc(n)
        try:
            k = np.stack([b["k"] for b in blocks], axis=2)
            v = np.stack([b["v"] for b in blocks], axis=2)
            ks = vs = None
            if blocks[0]["ks"] is not None:
                ks = np.stack([b["ks"] for b in blocks], axis=2)
                vs = np.stack([b["vs"] for b in blocks], axis=2)
            if ks is not None and not self.cache_quant:
                # int8-quantized tier over an fp pool: dequantize on
                # host (same absmax/127 scheme as the engine's int8
                # cache) and scatter full-precision values
                from ..serving.kvtier import _dequantize_host
                k = _dequantize_host(k, ks)
                v = _dequantize_host(v, vs)
                ks = vs = None
            self._scatter_host_kv(new_pages, k, v, ks, vs)
        except BaseException:
            # scatter failed mid-restore: the fresh pages were never
            # mapped or indexed — return them or they leak
            self.pool.decref(new_pages)
            raise
        all_pages = pages + new_pages
        new_cached = cached + n * self.page_size
        self.prefix_cache.insert(feed, all_pages, new_cached)
        tier.note_lookup(n)
        if req is not None:
            _tl_mark(req, "restore")
        _flight.record(
            "kvtier.hit", rid=None if req is None else str(req.rid),
            trace_id=None if req is None
            else getattr(req, "_trace_id", None),
            pages=n, tokens=n * self.page_size,
            device_cached=cached)
        return all_pages, new_cached

    def _spill_page(self, page, parent, block, depth):
        """Prefix-cache eviction hook: demote the page's KV to the
        host tier instead of discarding it. Slicing the pools HERE
        (pump thread) pins the page's current contents — jax arrays
        are functional, so the slices stay valid while the allocator
        re-issues the page and later steps overwrite it; the blocking
        device->host fence runs on the tier's copy thread."""
        self.host_tier.spill_async(
            parent, block, depth,
            self.k_pool[:, :, page], self.v_pool[:, :, page],
            None if self.k_scale is None else self.k_scale[:, :, page],
            None if self.v_scale is None else self.v_scale[:, :, page],
            prequantized=self.cache_quant)

    def _cache_unacquire(self, req):
        """Admission did not take the candidate after all: drop its
        acquired prefix (rc==0 pages fall back into the cache LRU)."""
        match = getattr(req, "_kv_match", None)
        if match and match[0]:
            self.pool.decref(match[0])
        req._kv_match = None

    def _map_prefix(self, slot, match):
        """Map already-acquired shared prefix pages into the slot's
        page-table row and pre-seed its length to the cached token
        count — the device only ever sees the suffix."""
        pages, cached = match
        self._seq_pages[slot] = list(pages)
        for i, pg in enumerate(pages):
            self.page_table[slot, i] = pg
        self.lengths[slot] = cached

    def _index_slot(self, slot, req):
        """Index the slot's full pages under the chained block hash of
        the tokens they hold (cache position i holds the KV of token
        (prompt+output)[i]) so later admissions can share them."""
        pc = self.prefix_cache
        if pc is None or self._index_suspend:
            return
        L = int(self.lengths[slot])
        toks = (list(req.prompt) + [int(t) for t in req.output])[:L]
        pc.insert(toks, self._seq_pages[slot], L)

    def _note_prefix_admit(self, req, match):
        """Admission-time cache accounting. Only admitted requests
        count — a queued candidate re-probed every step is not a
        stream of lookups."""
        pc = self.prefix_cache
        if pc is None:
            return
        cached = match[1]
        req.cached_tokens = cached
        pc.lookups += 1
        if cached > 0:
            pc.hits += 1
            pc.tokens_reused += cached
            _flight.record("kvcache.hit", rid=str(req.rid),
                           trace_id=getattr(req, "_trace_id", None),
                           cached_tokens=cached, pages=len(match[0]))
        m = self.metrics
        if m is not None:
            m.on_prefix_lookup(cached)

    def _note_prefix_evict(self, page):
        m = self.metrics
        if m is not None:
            m.on_prefix_evict()

    def _prefill_suffix_into(self, slot, req, match):
        """Suffix-only prefill for a prefix-cache hit: the matched
        pages are mapped in shared (ref-counted) and ONLY the
        remaining tokens run through the device — one bucket-shaped
        verify_step whose chunk attends to the cached pages through
        the slot's page table. The chunk/cache split is exactly the
        verify kernel's contract, so no new jitted entry point (and
        no new compile telemetry surface) is needed; partial-page
        prompt tails are part of the suffix and recomputed."""
        pages, cached = match
        feed = self._feed_ids(req)
        suffix = feed[cached:]
        n = len(suffix)
        self.prefill_tokens += n
        _tl_count(req, "prefill")
        self._map_prefix(slot, match)
        total = -(-len(feed) // self.page_size)
        if total > len(pages):
            self._alloc_pages(slot, total - len(pages))
        # bucketed chunk width: one compile per bucket, not one per
        # distinct suffix length (same reasoning as the packed
        # prefill scatter above)
        G = self._bucket_for(n)
        tokens = np.zeros((self.max_seqs, G), np.int64)
        tokens[slot, :n] = suffix
        n_tok = np.zeros((self.max_seqs,), np.int32)
        n_tok[slot] = n
        active = np.zeros((self.max_seqs,), bool)
        active[slot] = True
        self._fire("suffix_prefill", rids=[str(req.rid)])
        need = None
        if self.lean:
            # lean epilogue: only the chunk's final row seeds the first
            # generated token — one row of unembed FLOPs, not B*G
            need = jnp.asarray([slot * G + n - 1], jnp.int32)
            self.logit_rows += 1
            self.logit_rows_skipped += self.max_seqs * G - 1
        else:
            self.logit_rows += self.max_seqs * G
        with record_span("serving.prefill"):
            (self.k_pool, self.v_pool, self.k_scale, self.v_scale,
             logits) = verify_step(
                self.params, self.k_pool, self.v_pool,
                jnp.asarray(self.page_table.copy()),
                jnp.asarray(self.lengths.copy()),
                jnp.asarray(tokens), jnp.asarray(n_tok),
                jnp.asarray(active), self.config, self.page_size,
                use_pallas=self._use_pallas, interpret=self._interpret,
                k_scale=self.k_scale, v_scale=self.v_scale,
                mesh=self._mesh, need_rows=need)
        self.lengths[slot] = cached + n
        req.slot = slot
        req._admit_order = self._order
        self._order += 1
        self._attach(slot, req)
        self._note_prefix_admit(req, match)
        self._index_slot(slot, req)
        if getattr(req, "_resume", False):
            req._resume = False  # next_token survives from before eviction
        else:
            row = self._fetch_results(
                logits[0] if need is not None else logits[slot, n - 1])
            self._seed_first_token(slot, req, row)

    def run(self, max_steps=10000):
        steps = 0
        while (self._live or self._waiting) and steps < max_steps:
            self.step()
            steps += 1
        return self.finished

    def run_pipelined(self, max_steps=10000):
        """Drive the engine with the depth-1 double-buffered loop (the
        scheduler's pipelined pump uses the same step_launch /
        step_finish pair): launch step N+1 before consuming step N, so
        the host bookkeeping overlaps the in-flight device program.
        Token-identical to `run()` — greedy and seeded sampling both,
        because sampling happens inside the step keyed by (seed,
        position). Spec-decode engines fall back to the synchronous
        loop (drafting needs host-current context). Cancellation must
        only be applied between consumed steps — drive cancels through
        the scheduler, which drains the pipeline first."""
        if self.spec_decode > 1:
            return self.run(max_steps=max_steps)
        pending = None
        steps = 0
        while steps < max_steps and (self._live or self._waiting
                                     or pending is not None):
            try:
                ticket = self.step_launch(carry=pending)
            except PipelineStall:
                self.step_finish(pending)
                pending = None
                ticket = self.step_launch()
            if pending is not None:
                self.step_finish(pending, inflight=ticket)
            pending = ticket
            steps += 1
        if pending is not None:
            self.step_finish(pending)
        return self.finished
