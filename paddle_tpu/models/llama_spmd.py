"""Llama 4D-parallel pretrain step — the fleet-equivalent SPMD path.

Replaces the reference's fleet hybrid-parallel Llama pretrain
(python/paddle/distributed/fleet/meta_parallel/* + PaddleNLP llm/
modeling_pp.py) with a single pure train-step program:

  * layer params stacked (L, ...) → lax.scan over layers (pp=1) or
    grouped (pp, L/pp, ...) and pipelined via shard_map+ppermute (pp>1).
  * tp: megatron specs on weight axes (GSPMD inserts collectives).
  * dp: batch sharding (grad psum from GSPMD).
  * sp: optional ring attention over an 'sp' axis for long context.
  * remat: jax.checkpoint around each decoder layer.
  * AdamW with fp32 master weights; params bf16 on TPU.
"""
from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.rope import rope_cos_sin, apply_rotary_emb
from ..ops.flash_attention import flash_attention_bhsd
from ..ops.flashmask_attention import flashmask_attention_bhsd
from ..parallel.pp import (pipeline_apply, pipeline_train_1f1b,
                           pipeline_train_interleaved, group_stages,
                           group_virtual_stages, ungroup_virtual_stages)
from ..parallel.ring import ring_attention
from ..parallel.ulysses import ulysses_attention
from .llama import LlamaConfig


# ---------------------------------------------------------------- params
def init_params(config: LlamaConfig, seed=0, dtype=jnp.float32):
    c = config
    key = jax.random.key(seed)
    ks = jax.random.split(key, 12)
    H, F_, V, L = c.hidden_size, c.intermediate_size, c.vocab_size, \
        c.num_hidden_layers
    KV = c.num_key_value_heads * (H // c.num_attention_heads)
    std = c.initializer_range

    def w(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(dtype)

    params = {
        "embed": w(ks[0], (V, H)),
        "final_norm": jnp.ones((H,), dtype),
        "lm_head": w(ks[1], (H, V)),
        "layers": {
            "ln1": jnp.ones((L, H), dtype),
            "wq": w(ks[2], (L, H, H)),
            "wk": w(ks[3], (L, H, KV)),
            "wv": w(ks[4], (L, H, KV)),
            "wo": w(ks[5], (L, H, H)),
            "ln2": jnp.ones((L, H), dtype),
            "w_gate": w(ks[6], (L, H, F_)),
            "w_up": w(ks[7], (L, H, F_)),
            "w_down": w(ks[8], (L, F_, H)),
        },
    }
    return params


def param_specs(config, mesh, pp=False, fsdp_axis=None):
    """PartitionSpecs: megatron TP on weight axes; stacked layer axis over
    'pp' when pipelining; optional fsdp sharding of the embed/lm_head."""
    tp = "tp" if "tp" in mesh.shape else None
    ppax = "pp" if (pp and "pp" in mesh.shape) else None
    specs = {
        "embed": P(tp, None),
        "final_norm": P(),
        "lm_head": P(None, tp),
        "layers": {
            "ln1": P(ppax, None),
            "wq": P(ppax, None, tp),
            "wk": P(ppax, None, tp),
            "wv": P(ppax, None, tp),
            "wo": P(ppax, tp, None),
            "ln2": P(ppax, None),
            "w_gate": P(ppax, None, tp),
            "w_up": P(ppax, None, tp),
            "w_down": P(ppax, tp, None),
        },
    }
    return specs


# ---------------------------------------------------------------- forward
def _rms(x, g, eps):
    xf = x.astype(jnp.float32)
    out = xf * lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
    return (out * g.astype(jnp.float32)).astype(x.dtype)


def doc_end_indices(doc_ids):
    """(B, S) contiguous per-token document ids → (B, 1, S, 1) FlashMask
    startend_row_indices: for key column j, the first row that must NOT
    attend to it (= its document's end boundary). jit-safe."""
    B, S = doc_ids.shape
    idx = jnp.arange(S)
    is_last = jnp.concatenate(
        [doc_ids[:, 1:] != doc_ids[:, :-1], jnp.ones((B, 1), bool)], axis=1)
    cand = jnp.where(is_last, idx + 1, S + 1)
    end = lax.cummin(cand[:, ::-1], axis=1)[:, ::-1]
    return end.astype(jnp.int32)[:, None, :, None]


def decoder_layer(lp, h, rope, config: LlamaConfig, sp_axis=None,
                  sp_impl="ring", mesh=None):
    """One decoder layer, pure. h: (B, S, H). rope: (cos, sin) or
    (cos, sin, sri) where sri is a FlashMask startend_row_indices
    tensor (B, 1, S_k, n) for packed-document attention.

    sp_impl: context-parallel scheme when sp_axis is set — "ring"
    (K/V rotation, scales past head count) or "ulysses" (all-to-all
    head<->sequence re-shard, full local flash kernel; needs
    heads % sp == 0). See parallel/ulysses.py for the trade. The
    attention is wrapped in its own shard_map over `mesh` (required
    with sp_axis): plain jit/GSPMD never binds named axes, so the
    _local collectives cannot be called bare from here."""
    c = config
    cos, sin = rope[0], rope[1]
    sri = rope[2] if len(rope) > 2 else None
    nh = c.num_attention_heads
    nkv = c.num_key_value_heads
    hd = c.hidden_size // nh
    b, s, H = h.shape

    x = _rms(h, lp["ln1"], c.rms_norm_eps)
    q = (x @ lp["wq"]).reshape(b, s, nh, hd).swapaxes(1, 2)
    k = (x @ lp["wk"]).reshape(b, s, nkv, hd).swapaxes(1, 2)
    v = (x @ lp["wv"]).reshape(b, s, nkv, hd).swapaxes(1, 2)
    q, k = apply_rotary_emb(q, k, cos[None, None], sin[None, None])
    rep = nh // nkv
    if rep > 1 and not (sp_axis is not None and sp_impl == "ulysses"):
        # ulysses takes GQA K/V unrepeated: it moves them over ICI at
        # kv width and repeats after the re-shard (rep× fewer wire
        # bytes); every other path wants full-head K/V here
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if sp_axis is not None:
        if mesh is None:
            raise ValueError(
                "decoder_layer(sp_axis=...) needs the mesh: the "
                "context-parallel attention runs under its own "
                "shard_map; without it the named axis is unbound")
        attn = ulysses_attention if sp_impl == "ulysses" else ring_attention
        o = attn(q, k, v, mesh, sp_axis, causal=True)
    elif sri is not None:
        # packed-document pretraining: causal within each document,
        # blocked across documents — flashmask kernel, no dense mask
        sri_h = jnp.broadcast_to(sri, (b, nh, s, sri.shape[-1]))
        o = flashmask_attention_bhsd(q, k, v, sri_h, causal=True)
    else:
        o = flash_attention_bhsd(q, k, v, causal=True)
    attn_out = o.swapaxes(1, 2).reshape(b, s, H) @ lp["wo"]
    h = h + attn_out

    x = _rms(h, lp["ln2"], c.rms_norm_eps)
    mlp = (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
    return h + mlp


def forward(params, input_ids, config: LlamaConfig, mesh=None, n_micro=None,
            remat=True, sp_axis=None, doc_ids=None, return_hidden=False,
            sp_impl="ring"):
    """→ logits (B, S, V). Uses pipeline when mesh has pp>1, else scan.

    doc_ids: optional (B, S) contiguous document ids for packed-sequence
    pretraining — attention stays causal within a document and is
    blocked across documents via the FlashMask kernel (no dense mask).

    return_hidden: return the final-norm'd hidden states (B, S, H)
    WITHOUT the lm_head projection — the fused linear+cross-entropy
    loss path consumes these directly so the (B, S, V) logits are never
    materialized.
    """
    c = config
    s = input_ids.shape[1]
    cos, sin = rope_cos_sin(s, c.hidden_size // c.num_attention_heads,
                            c.rope_theta, jnp.float32)
    extra = (cos, sin)
    if doc_ids is not None:
        if mesh is not None and mesh.shape.get("pp", 1) > 1:
            raise NotImplementedError(
                "packed-document flashmask + pipeline parallelism: the "
                "per-row mask cannot ride the replicated pipeline extra "
                "yet — use doc_ids without pp, or pp without doc_ids")
        if sp_axis is not None:
            raise NotImplementedError(
                "packed-document flashmask + sequence parallelism is "
                "not supported: neither the ring nor the ulysses "
                "context-parallel attention carries a document mask — "
                "drop sp_axis or doc_ids")
        extra = (cos, sin, doc_end_indices(doc_ids))
    h = jnp.take(params["embed"], input_ids, axis=0)

    use_pp_ = mesh is not None and mesh.shape.get("pp", 1) > 1
    if sp_axis is not None and use_pp_:
        raise NotImplementedError(
            "sequence parallelism inside the pp pipeline is not "
            "supported: the attention's shard_map cannot nest inside "
            "the pipeline's — shard sequence on a pp=1 mesh, or drop "
            "sp_axis")
    layer = functools.partial(decoder_layer, config=c, sp_axis=sp_axis,
                              sp_impl=sp_impl, mesh=mesh)
    if remat == "dots":
        # save matmul outputs, recompute only elementwise — ~MFU win over
        # full remat when activations still fit in HBM
        layer = jax.checkpoint(
            layer, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif remat:
        layer = jax.checkpoint(layer)

    use_pp = mesh is not None and mesh.shape.get("pp", 1) > 1
    if use_pp:
        n_stages = mesh.shape["pp"]
        staged = group_stages(params["layers"], n_stages)
        h = pipeline_apply(staged, h,
                           lambda lp, hh, extra_: layer(lp, hh, extra_),
                           mesh, pp_axis="pp", n_micro=n_micro,
                           extra=extra)
    else:
        def body(hh, lp):
            return layer(lp, hh, extra), None
        h, _ = lax.scan(body, h, params["layers"])

    h = _rms(h, params["final_norm"], c.rms_norm_eps)
    if return_hidden:
        return h
    return h @ params["lm_head"]


def _masked_nll(logits, labels):
    """→ (nll_sum, valid_count): summed next-token NLL over labels >= 0
    (labels < 0 are the ignore sentinel, e.g. document boundaries).
    Single source for every loss path so semantics cannot drift."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logp, jnp.maximum(labels, 0)[..., None].astype(jnp.int32),
        axis=-1)[..., 0]
    valid = (labels >= 0).astype(jnp.float32)
    return -jnp.sum(picked * valid), jnp.sum(valid)


# default vocab-chunk for the fused linear+CE path; 8192 keeps the live
# (N, chunk) logits slab ~64 MB at N=32k tokens vs 4 GB for full fp32
# (B, S, V) logits at V=32000
FUSED_CE_CHUNK = 8192


def _fused_masked_nll(h, lm_head, labels, chunk=FUSED_CE_CHUNK):
    """(nll_sum, valid_count) via ops.fused.fused_linear_cross_entropy:
    the (B, S, V) logits are never materialized — vocab is streamed in
    chunks with an online logsumexp (reference parity:
    paddle/phi/kernels/gpu/cross_entropy_kernel.cu softmax+CE fusion).
    Same semantics as _masked_nll(h @ lm_head, labels)."""
    from ..ops.fused import fused_linear_cross_entropy
    B, S, H = h.shape
    x = h.reshape(B * S, H)
    lab = labels.reshape(B * S).astype(jnp.int32)
    valid = lab >= 0
    per_tok = fused_linear_cross_entropy(
        x, lm_head, jnp.where(valid, lab, 0), chunk_size=chunk,
        reduction="none")
    per_tok = jnp.where(valid, per_tok, 0.0)
    return jnp.sum(per_tok), jnp.sum(valid.astype(jnp.float32))


def _resolve_fused_ce(fused_ce):
    """None → the PT_FUSED_CE env knob (bench/autotune sweep surface)."""
    if fused_ce is None:
        import os
        return os.environ.get("PT_FUSED_CE", "0") == "1"
    return bool(fused_ce)


def loss_fn(params, batch, config, mesh=None, n_micro=None, remat=True,
            sp_axis=None, fused_ce=False, sp_impl="ring"):
    """batch: (input_ids, labels) or (input_ids, labels, doc_ids) for
    packed-document pretraining. Labels < 0 are ignored (masked mean)."""
    s, n = loss_sum_fn(params, batch, config, mesh, n_micro, remat, sp_axis,
                       fused_ce=fused_ce, sp_impl=sp_impl)
    return s / jnp.maximum(n, 1.0)


def loss_sum_fn(params, batch, config, mesh=None, n_micro=None, remat=True,
                sp_axis=None, fused_ce=False, sp_impl="ring"):
    """(nll_sum, valid_count) variant — the grad-accumulation path
    accumulates these so microbatches are weighted by their VALID token
    counts, keeping n_micro=k exactly equal to the one-shot step even
    with unevenly distributed ignore-labels.

    fused_ce=True routes the head through the fused linear+CE op (no
    logits materialization) — numerically equivalent, big activation-
    memory/HBM win at large vocab."""
    input_ids, labels = batch[0], batch[1]
    doc_ids = batch[2] if len(batch) > 2 else None
    if fused_ce:
        h = forward(params, input_ids, config, mesh, n_micro, remat, sp_axis,
                    doc_ids=doc_ids, return_hidden=True, sp_impl=sp_impl)
        return _fused_masked_nll(h, params["lm_head"], labels)
    logits = forward(params, input_ids, config, mesh, n_micro, remat, sp_axis,
                     doc_ids=doc_ids, sp_impl=sp_impl)
    return _masked_nll(logits, labels)


# ---------------------------------------------------------------- training
def init_opt_state(params):
    return jax.tree_util.tree_map(
        lambda p: {"m": jnp.zeros_like(p, dtype=jnp.float32),
                   "v": jnp.zeros_like(p, dtype=jnp.float32),
                   # copy=True: master must not alias the param buffer
                   # (both pytrees are donated to the train step)
                   "master": jnp.array(p, dtype=jnp.float32, copy=True)}, params)


def adamw_update(params, grads, state, lr, step, b1=0.9, b2=0.95, eps=1e-8,
                 wd=0.1):
    t = step.astype(jnp.float32) + 1.0

    def upd(p, g, s):
        g = g.astype(jnp.float32)
        m = b1 * s["m"] + (1 - b1) * g
        v = b2 * s["v"] + (1 - b2) * g * g
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        master = s["master"] * (1 - lr * wd) - lr * mhat / (jnp.sqrt(vhat) + eps)
        return master.astype(p.dtype), {"m": m, "v": v, "master": master}

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = jax.tree_util.tree_flatten(grads)[0]
    flat_s = treedef.flatten_up_to(state)
    outs = [upd(p, g, s) for p, g, s in zip(flat_p, flat_g, flat_s)]
    new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
    new_s = jax.tree_util.tree_unflatten(treedef, [o[1] for o in outs])
    return new_p, new_s


def make_train_step(config, mesh, batch_spec=P("dp"), n_micro=None, remat=True,
                    clip_norm=1.0, lr=3e-4, sp_axis=None, donate=True,
                    schedule=None, fused_ce=None, vpp=2, sp_impl="ring"):
    """Build the jitted 4D-parallel train step.

    (params, opt_state, step, batch) → (params, opt_state, loss)

    schedule: with pp>1, "gpipe" runs the differentiable scan pipeline
    (AD backward, O(n_micro) stashed activations), "1f1b" runs the
    hand-seeded one-forward-one-backward schedule (O(pp) stashed stage
    inputs — reference pipeline_parallel.py:958 parity), and
    "interleave" runs interleaved virtual-stage 1F1B with `vpp` layer
    chunks per stage — fill/drain bubble divided by vpp (reference
    pipeline_parallel.py:1309). None (default) consults fleet's
    strategy.pipeline_configs['schedule_mode'] when fleet.init ran,
    else "gpipe". NB interleave keeps the contiguous (L, ...) param
    layout at rest; the step regroups to the chunked layout under jit,
    so GSPMD inserts a per-step layer-param reshuffle over the pp axis
    — store-interleaved layouts are a future optimization.

    fused_ce: route every loss path through the fused linear+CE op so
    the (B, S, V) logits never materialize (reference:
    phi/kernels/gpu/cross_entropy_kernel.cu fusion). None consults the
    PT_FUSED_CE env knob so bench.py/autotune can sweep it.
    """
    fused_ce = _resolve_fused_ce(fused_ce)
    if schedule is None:
        schedule = "gpipe"
        try:
            from ..distributed.fleet import fleet as _fleet
            if getattr(_fleet, "_is_initialized", False):
                schedule = _fleet.pipeline_schedule()
                if schedule == "interleave":
                    fleet_vpp = _fleet.virtual_pp_degree()
                    if fleet_vpp <= 1:
                        # never silently pick a virtual degree the user
                        # didn't configure (fleet policy: no silent
                        # downgrades/upgrades of the memory profile)
                        raise ValueError(
                            "schedule_mode 'interleave' needs "
                            "hybrid_configs pp_configs virtual_pp_degree "
                            ">= 2 (got "
                            f"{fleet_vpp}); set it, or pass vpp= "
                            "explicitly with schedule='interleave'")
                    vpp = fleet_vpp
        except ImportError:  # pragma: no cover
            pass
    use_pp = mesh.shape.get("pp", 1) > 1
    specs = param_specs(config, mesh, pp=use_pp)
    pshard = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                    is_leaf=lambda x: isinstance(x, P))
    sshard = jax.tree_util.tree_map(
        lambda sh: {"m": sh, "v": sh, "master": sh}, pshard,
        is_leaf=lambda x: isinstance(x, NamedSharding))
    repl = NamedSharding(mesh, P())
    bshard = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), batch_spec,
                                    is_leaf=lambda x: isinstance(x, P))

    def grads_pipelined(params, batch):
        """Loss + grads via the hand-seeded pipeline (1F1B or
        interleaved vpp): embed lookup and its scatter-grad run
        replicated outside the pipeline; final-norm + lm_head + loss
        fold into head_fn on the last stage."""
        c = config
        if len(batch) > 2:
            raise NotImplementedError(
                "packed-document flashmask + 1F1B pipeline is not "
                "supported yet (see forward()'s doc_ids + pp note)")
        if sp_axis is not None:
            raise NotImplementedError(
                "sequence parallelism inside the 1F1B/interleave "
                "pipeline is not supported (see forward()'s sp + pp "
                "note)")
        input_ids, labels = batch[0], batch[1]
        s = input_ids.shape[1]
        cos, sin = rope_cos_sin(s, c.hidden_size // c.num_attention_heads,
                                c.rope_theta, jnp.float32)
        layer = functools.partial(decoder_layer, config=c)
        if remat == "dots":
            layer = jax.checkpoint(
                layer,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        elif remat:
            layer = jax.checkpoint(layer)

        h0, pull_embed = jax.vjp(
            lambda e: jnp.take(e, input_ids, axis=0), params["embed"])

        def head_fn(hp, h, tgt):
            # returns (nll_sum, valid_count): pipeline_train_1f1b
            # normalizes by the GLOBAL valid count, so microbatches are
            # weighted by their valid tokens — identical loss/grad
            # semantics to the no-pp and grad-accum paths even with
            # uneven ignore-label masking.
            hh = _rms(h, hp["final_norm"], c.rms_norm_eps)
            if fused_ce:
                return _fused_masked_nll(hh, hp["lm_head"], tgt)
            logits = hh @ hp["lm_head"]
            return _masked_nll(logits, tgt)

        n_stages = mesh.shape["pp"]
        head_p = {"final_norm": params["final_norm"],
                  "lm_head": params["lm_head"]}
        layer_fn = lambda lp, hh, extra: layer(lp, hh, extra)
        if schedule == "interleave":
            staged = group_virtual_stages(params["layers"], n_stages, vpp)
            loss, gstage, ghead, dh0 = pipeline_train_interleaved(
                staged, h0, labels, layer_fn, head_fn, head_p, mesh,
                pp_axis="pp", n_micro=n_micro, vpp=vpp, extra=(cos, sin))
            g_layers = ungroup_virtual_stages(gstage, n_stages, vpp)
        else:
            staged = group_stages(params["layers"], n_stages)
            loss, gstage, ghead, dh0 = pipeline_train_1f1b(
                staged, h0, labels, layer_fn, head_fn, head_p, mesh,
                pp_axis="pp", n_micro=n_micro, extra=(cos, sin))
            L = c.num_hidden_layers
            g_layers = jax.tree_util.tree_map(
                lambda a: a.reshape(L, *a.shape[2:]), gstage)
        (g_embed,) = pull_embed(dh0.astype(h0.dtype))
        grads = {"embed": g_embed, "final_norm": ghead["final_norm"],
                 "lm_head": ghead["lm_head"], "layers": g_layers}
        return loss, grads

    def step_fn(params, opt_state, step, batch):
        if use_pp and schedule in ("1f1b", "interleave"):
            loss, grads = grads_pipelined(params, batch)
        elif n_micro and n_micro > 1 and not use_pp:
            # true gradient accumulation: scan over n_micro microbatches,
            # summing fp32 grads. Peak activation memory drops ~n_micro×
            # (one microbatch's activations live at a time) at the cost
            # of a serial loop — can unlock a bigger global batch or a
            # lighter remat policy. With pp, n_micro instead feeds the
            # pipeline schedule (forward() above).
            B = batch[0].shape[0]
            assert B % n_micro == 0, (
                f"batch {B} not divisible by n_micro={n_micro}")
            mb = B // n_micro
            parts = tuple(p.reshape(n_micro, mb, *p.shape[1:])
                          for p in batch)

            # accumulate SUMMED NLL + valid counts so microbatches are
            # weighted by their valid-token counts — exactly equal to
            # the one-shot step even with uneven ignore-labels
            def micro(acc, mb_batch):
                acc_s, acc_n, acc_g = acc

                def sum_only(p):
                    # mesh only when sp is on (the attention shard_map
                    # needs it); None otherwise keeps the microbatch
                    # forward off the pp pipeline path
                    s, n = loss_sum_fn(p, mb_batch, config,
                                       mesh if sp_axis else None, None,
                                       remat, sp_axis, fused_ce=fused_ce,
                                       sp_impl=sp_impl)
                    return s, n
                (s, n), g = jax.value_and_grad(sum_only, has_aux=True)(params)
                acc_g = jax.tree_util.tree_map(
                    lambda a, b: a + b.astype(jnp.float32), acc_g, g)
                return (acc_s + s, acc_n + n, acc_g), None

            zero_g = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (loss_s, loss_n, grads), _ = lax.scan(
                micro, (jnp.float32(0.0), jnp.float32(0.0), zero_g), parts)
            denom = jnp.maximum(loss_n, 1.0)
            loss = loss_s / denom
            grads = jax.tree_util.tree_map(lambda g: g / denom, grads)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch, config,
                mesh if (use_pp or sp_axis) else None, n_micro,
                remat, sp_axis, fused_ce, sp_impl)
        if clip_norm is not None:
            leaves = jax.tree_util.tree_leaves(grads)
            gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                              for g in leaves))
            scale = clip_norm / jnp.maximum(gn, clip_norm)
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
        params, opt_state = adamw_update(params, grads, opt_state, lr, step)
        return params, opt_state, loss

    return jax.jit(
        step_fn,
        # batch may be (ids, labels) or (ids, labels, doc_ids): shard
        # every element the same way without pinning the arity
        in_shardings=(pshard, sshard, None, bshard),
        out_shardings=(pshard, sshard, repl),
        donate_argnums=(0, 1) if donate else ())


def place_params(params, config, mesh, pp=None):
    if pp is None:
        pp = mesh.shape.get("pp", 1) > 1
    specs = param_specs(config, mesh, pp=pp)
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_s = treedef.flatten_up_to(specs)
    placed = [jax.device_put(p, NamedSharding(mesh, s))
              for p, s in zip(flat_p, flat_s)]
    return jax.tree_util.tree_unflatten(treedef, placed)
