"""MoE decoder LLM — DeepSeekMoE / Qwen2-MoE shape (BASELINE config 5:
"DeepSeekMoE / Qwen2-MoE expert-parallel (fleet EP over ICI)").

Llama-style blocks where the dense MLP is replaced by a routed MoE FFN
(shared + routed experts, top-k gating, load-balance aux loss) riding
the 'ep' mesh axis via GSPMD all_to_all.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..parallel.moe import MoELayer
from .llama import LlamaAttention, LlamaConfig


@dataclass
class MoEConfig(LlamaConfig):
    num_experts: int = 8
    num_experts_per_tok: int = 2
    num_shared_experts: int = 1
    moe_intermediate_size: int = 0  # 0 → intermediate_size
    aux_loss_weight: float = 0.01

    @classmethod
    def tiny_moe(cls):
        return cls(vocab_size=256, hidden_size=64, intermediate_size=128,
                   num_hidden_layers=2, num_attention_heads=4,
                   num_key_value_heads=2, max_position_embeddings=128,
                   num_experts=4, num_experts_per_tok=2, num_shared_experts=1)


class MoEDecoderLayer(nn.Layer):
    def __init__(self, config: MoEConfig):
        super().__init__()
        c = config
        self.input_layernorm = nn.RMSNorm(c.hidden_size, epsilon=c.rms_norm_eps)
        self.self_attn = LlamaAttention(c)
        self.post_attention_layernorm = nn.RMSNorm(c.hidden_size,
                                                   epsilon=c.rms_norm_eps)
        d_ff = c.moe_intermediate_size or c.intermediate_size
        self.mlp = MoELayer(c.hidden_size, d_ff, c.num_experts,
                            top_k=c.num_experts_per_tok,
                            num_shared_experts=c.num_shared_experts)

    def forward(self, x, cos, sin):
        h = x + self.self_attn(self.input_layernorm(x), cos, sin)
        return h + self.mlp(self.post_attention_layernorm(h))


class MoEForCausalLM(nn.Layer):
    def __init__(self, config: MoEConfig):
        super().__init__()
        self.config = config
        c = config
        from ..nn.initializer import Normal
        attr = nn.ParamAttr(initializer=Normal(0.0, c.initializer_range))
        self.embed_tokens = nn.Embedding(c.vocab_size, c.hidden_size,
                                         weight_attr=attr)
        self.layers = nn.LayerList([MoEDecoderLayer(c)
                                    for _ in range(c.num_hidden_layers)])
        self.norm = nn.RMSNorm(c.hidden_size, epsilon=c.rms_norm_eps)
        self.lm_head = nn.Linear(c.hidden_size, c.vocab_size, weight_attr=attr,
                                 bias_attr=False)

    def forward(self, input_ids, labels=None):
        from ..ops.rope import rope_cos_sin
        c = self.config
        s = input_ids.shape[1]
        cos, sin = rope_cos_sin(s, c.hidden_size // c.num_attention_heads,
                                c.rope_theta)
        x = self.embed_tokens(input_ids)
        aux_total = None
        for layer in self.layers:
            x = layer(x, cos, sin)
            aux = layer.mlp.aux_loss
            aux_total = aux if aux_total is None else aux_total + aux
        logits = self.lm_head(self.norm(x))
        if labels is not None:
            loss = F.cross_entropy(logits, labels)
            if aux_total is not None:
                loss = loss + self.config.aux_loss_weight * aux_total
            return loss, logits
        return logits
