"""Qwen2 family (reference: PaddleNLP paddlenlp/transformers/qwen2).

Architecturally Llama with QKV projection biases (and tied embeddings on
the small variants) — we reuse the Llama stack and swap the attention
projection construction, keeping the same TP dist_specs.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from .._core.tensor import Tensor, apply
from ..nn.initializer import Normal
from ..ops.flash_attention import flash_attention_bhsd
from ..ops.rope import apply_rotary_emb
from jax.sharding import PartitionSpec as P

from .llama import (LlamaConfig, LlamaMLP, LlamaModel, LlamaForCausalLM,
                    LlamaDecoderLayer, LlamaAttention)


@dataclass(unsafe_hash=True)
class Qwen2Config(LlamaConfig):
    attention_bias: bool = True
    tie_word_embeddings: bool = True

    @classmethod
    def qwen2_7b(cls):
        return cls(vocab_size=152064, hidden_size=3584,
                   intermediate_size=18944, num_hidden_layers=28,
                   num_attention_heads=28, num_key_value_heads=4,
                   max_position_embeddings=32768, rope_theta=1e6,
                   tie_word_embeddings=False)

    @classmethod
    def qwen2_0_5b(cls):
        return cls(vocab_size=151936, hidden_size=896, intermediate_size=4864,
                   num_hidden_layers=24, num_attention_heads=14,
                   num_key_value_heads=2, max_position_embeddings=32768,
                   rope_theta=1e6, tie_word_embeddings=True)


class Qwen2Attention(LlamaAttention):
    def __init__(self, config, tp_axis="tp"):
        super().__init__(config, tp_axis)
        if getattr(config, "attention_bias", True):
            h = config.hidden_size
            kv = self.num_kv_heads * self.head_dim
            z = nn.initializer.Constant(0.0)
            for name, width in (("q_proj", h), ("k_proj", kv), ("v_proj", kv)):
                layer = getattr(self, name)
                layer.bias = layer.create_parameter(
                    [width], default_initializer=z, is_bias=True)

    def forward(self, x, cos, sin, kv_cache=None, causal=True):
        b, s, h = x.shape
        has_bias = self.q_proj.bias is not None

        def fn(xr, wq, wk, wv, wo, cosr, sinr, *rest):
            if has_bias:
                bq, bk, bv = rest[:3]
                cache = rest[3:]
            else:
                bq = bk = bv = None
                cache = rest
            q = xr @ wq + (bq if bq is not None else 0.0)
            k = xr @ wk + (bk if bk is not None else 0.0)
            v = xr @ wv + (bv if bv is not None else 0.0)
            q = q.reshape(b, s, self.num_heads, self.head_dim)
            k = k.reshape(b, s, self.num_kv_heads, self.head_dim)
            v = v.reshape(b, s, self.num_kv_heads, self.head_dim)
            q, k = apply_rotary_emb(q.swapaxes(1, 2), k.swapaxes(1, 2),
                                    cosr[None, None], sinr[None, None])
            v = v.swapaxes(1, 2)
            if cache:
                ck, cv = cache
                k = jnp.concatenate([ck, k], axis=2)
                v = jnp.concatenate([cv, v], axis=2)
            rep = self.num_heads // self.num_kv_heads
            if rep > 1:
                k = jnp.repeat(k, rep, axis=1)
                v = jnp.repeat(v, rep, axis=1)
            o = flash_attention_bhsd(q, k, v, causal=causal)
            return o.swapaxes(1, 2).reshape(b, s, h) @ wo

        args = [x, self.q_proj.weight, self.k_proj.weight, self.v_proj.weight,
                self.o_proj.weight, Tensor(cos), Tensor(sin)]
        if has_bias:
            args += [self.q_proj.bias, self.k_proj.bias, self.v_proj.bias]
        if kv_cache is not None:
            args += [kv_cache[0], kv_cache[1]]
        return apply(fn, *args, name="qwen2_attention")


class Qwen2DecoderLayer(LlamaDecoderLayer):
    def __init__(self, config):
        nn.Layer.__init__(self)
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.self_attn = Qwen2Attention(config)
        self.post_attention_layernorm = nn.RMSNorm(
            config.hidden_size, epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)


class Qwen2Model(LlamaModel):
    def __init__(self, config):
        super().__init__(config)
        self.layers = nn.LayerList([Qwen2DecoderLayer(config)
                                    for _ in range(config.num_hidden_layers)])


class Qwen2ForCausalLM(LlamaForCausalLM):
    def __init__(self, config):
        nn.Layer.__init__(self)
        self.config = config
        self.llama = Qwen2Model(config)
        if not config.tie_word_embeddings:
            self.lm_head = nn.Linear(
                config.hidden_size, config.vocab_size,
                weight_attr=nn.ParamAttr(
                    initializer=Normal(0.0, config.initializer_range)),
                bias_attr=False)
            self.lm_head.weight.dist_spec = P(None, "tp")
        else:
            self.lm_head = None
