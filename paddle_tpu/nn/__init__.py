"""paddle_tpu.nn (reference: python/paddle/nn/__init__.py)."""
from .layer.layers import Layer, ParamAttr  # noqa: F401
from .layer.container import Sequential, LayerList, LayerDict, ParameterList  # noqa: F401
from .layer.common import (  # noqa: F401
    Identity, Linear, Bilinear, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    FeatureAlphaDropout, Embedding, Flatten, Unflatten, Upsample,
    UpsamplingNearest2D, UpsamplingBilinear2D, Pad1D, Pad2D, Pad3D, ZeroPad1D,
    ZeroPad2D, ZeroPad3D, CosineSimilarity, PairwiseDistance, Unfold, Fold,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from .layer.pooling import (  # noqa: F401
    AvgPool1D, AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D, AdaptiveMaxPool1D,
    AdaptiveMaxPool2D, AdaptiveMaxPool3D, LPPool1D, LPPool2D, MaxUnPool1D,
    MaxUnPool2D, MaxUnPool3D,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm, LayerNorm,
    RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
)
from .layer.activation import (  # noqa: F401
    ReLU, ReLU6, SiLU, Swish, Sigmoid, LogSigmoid, Tanh, Tanhshrink, Softsign,
    Mish, Hardswish, ELU, CELU, SELU, GELU, Hardshrink, Hardsigmoid, Hardtanh,
    LeakyReLU, PReLU, RReLU, Softplus, Softshrink, ThresholdedReLU, Softmax,
    Softmax2D, LogSoftmax, Maxout, GLU,
)
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, SmoothL1Loss, HuberLoss, NLLLoss, BCELoss,
    BCEWithLogitsLoss, KLDivLoss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, TripletMarginWithDistanceLoss,
    MultiLabelSoftMarginLoss, SoftMarginLoss, MultiMarginLoss, CTCLoss,
    RNNTLoss, PoissonNLLLoss, GaussianNLLLoss,
)
from .layer.rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, SimpleRNN, LSTM, GRU,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layer.vision import PixelShuffle, PixelUnshuffle, ChannelShuffle  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm, clip_grad_norm_,
    clip_grad_value_,
)
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .layer import layers  # noqa: F401
from .layer.extra_layers import (  # noqa: F401
    ParameterDict, BiRNN, HSigmoidLoss, AdaptiveLogSoftmaxWithLoss,
    FractionalMaxPool2D, FractionalMaxPool3D,
)
from .layer.activation import SiLU as Silu  # noqa: F401  (paddle alias)
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
