"""Gradient clipping (reference: python/paddle/nn/clip.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .._core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._value.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._value * scale).astype(g.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        sq = [jnp.sum(jnp.square(g._value.astype(jnp.float32)))
              for p, g in params_grads
              if g is not None and getattr(p, "need_clip", True)]
        if not sq:
            return params_grads
        gnorm = jnp.sqrt(sum(sq[1:], sq[0]))
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._value * scale).astype(g.dtype))))
        return out

    @staticmethod
    def functional(grads_tree, clip_norm):
        """Pure clip for compiled train steps: tree of raw grads → clipped."""
        import jax
        leaves = jax.tree_util.tree_leaves(grads_tree)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in leaves))
        scale = clip_norm / jnp.maximum(gnorm, clip_norm)
        return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype),
                                      grads_tree), gnorm


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = parameters if isinstance(parameters, (list, tuple)) else [parameters]
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros((), jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.power(jnp.abs(g._value.astype(jnp.float32)),
                                                norm_type)) for g in grads),
                          1.0 / norm_type)
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        if p.grad is not None:
            p.grad = Tensor((p.grad._value * clip_coef).astype(p.grad.dtype))
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = parameters if isinstance(parameters, (list, tuple)) else [parameters]
    for p in params:
        if p.grad is not None:
            p.grad = Tensor(jnp.clip(p.grad._value, -clip_value, clip_value))
