"""Seq2seq decoding (reference: python/paddle/nn/decode.py —
BeamSearchDecoder + dynamic_decode).

TPU-shaped design: the beam state is a fixed-size (batch*beam) pytree the
whole way through — candidates are scored with one dense top-k over
beam*vocab per step, so every step is the same static-shape program. The
step loop itself is host-driven (dynamic_decode is an eager API in the
reference too); compiled KV-cache generation lives in models/*_decode.py.
"""
from __future__ import annotations

import jax
import numpy as np

from .._core.tensor import Tensor, unwrap

__all__ = ["BeamSearchDecoder", "dynamic_decode", "Decoder"]


class Decoder:
    """Abstract decoder interface (reference decode.py Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """reference: decode.py:163. Wraps a cell; scores live in log space;
    finished beams are locked to end_token with a one-hot -inf/0 score row
    so they never spawn new candidates."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers (reference tile_beam_merge_with_batch) -------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        import jax.numpy as jnp
        v = unwrap(x)
        v = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(v.reshape((-1,) + v.shape[2:]))

    def _merge(self, v):
        import jax.numpy as jnp
        return jnp.repeat(jnp.asarray(v)[:, None], self.beam_size,
                          axis=1).reshape((-1,) + tuple(v.shape[1:]))

    def initialize(self, inits):
        import jax.numpy as jnp
        states = jax.tree_util.tree_map(
            lambda t: Tensor(self._merge(unwrap(t))), inits,
            is_leaf=lambda t: isinstance(t, Tensor)) if inits is not None \
            else None
        # infer batch from the first state leaf
        leaves = jax.tree_util.tree_leaves(
            inits, is_leaf=lambda t: isinstance(t, Tensor))
        batch = unwrap(leaves[0]).shape[0] if leaves else 1
        bk = batch * self.beam_size
        tokens = jnp.full((bk,), self.start_token, jnp.int64)
        # only beam 0 is live initially (all beams identical otherwise)
        lp = jnp.where(jnp.arange(bk) % self.beam_size == 0, 0.0, -1e9)
        finished = jnp.zeros((bk,), bool)
        return tokens, (states, lp, finished, batch)

    def step(self, time, inputs, states, **kwargs):
        import jax
        import jax.numpy as jnp
        cell_states, log_probs, finished, batch = states
        emb = self.embedding_fn(Tensor(inputs)) if self.embedding_fn \
            else Tensor(inputs)
        out, new_cell_states = self.cell(emb, cell_states, **kwargs)
        if self.output_fn is not None:
            out = self.output_fn(out)
        logits = unwrap(out).astype(jnp.float32)
        vocab = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits, axis=-1)
        # finished beams only extend with end_token at zero cost
        fin_row = jnp.full((vocab,), -jnp.inf).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[:, None], fin_row[None, :], step_lp)
        total = log_probs[:, None] + step_lp              # (B*K, V)
        k = self.beam_size
        flat = total.reshape(batch, k * vocab)
        top_lp, top_idx = jax.lax.top_k(flat, k)          # (B, K)
        beam_src = top_idx // vocab                       # which parent beam
        tokens = (top_idx % vocab).astype(jnp.int64)
        # gather parent state rows: global row = b*k + beam_src
        gidx = (jnp.arange(batch)[:, None] * k + beam_src).reshape(-1)

        def pick(t):
            return Tensor(jnp.take(unwrap(t), gidx, axis=0))

        new_cell_states = jax.tree_util.tree_map(
            pick, new_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        new_finished = jnp.take(finished, gidx) | \
            (tokens.reshape(-1) == self.end_token)
        next_states = (new_cell_states, top_lp.reshape(-1), new_finished,
                       batch)
        return tokens.reshape(-1), next_states, new_finished

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """reference: decode.py:1238 — run decoder.initialize then step until
    every beam is finished or max_step_num. Returns (outputs, final
    states[, sequence_lengths])."""
    import jax.numpy as jnp
    inputs, states = decoder.initialize(inits)
    step_outputs = []
    lengths = None
    limit = max_step_num if max_step_num is not None else 256
    finished = None
    for t in range(limit):
        out, states, finished = decoder.step(t, inputs, states, **kwargs)
        step_outputs.append(np.asarray(out))
        fin_np = np.asarray(finished)
        if lengths is None:
            lengths = np.full(fin_np.shape, limit, np.int64)
        newly = (fin_np) & (lengths == limit)
        lengths[newly] = t + 1
        inputs = out
        if fin_np.all():
            break
    seq = np.stack(step_outputs, axis=0 if output_time_major else 1)
    outputs = Tensor(jnp.asarray(seq))
    outputs, states = decoder.finalize(outputs, states, lengths)
    if return_length:
        return outputs, states, Tensor(jnp.asarray(lengths))
    return outputs, states
