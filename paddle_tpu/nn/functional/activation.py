"""Activation functionals (reference: python/paddle/nn/functional/activation.py).

Pure jnp/jax.nn cores — XLA fuses these into adjacent matmuls/convs on
TPU, replacing phi's hand-written activation CUDA kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..._core.tensor import apply

__all__ = [
    "relu", "relu_", "relu6", "elu", "elu_", "celu", "selu", "gelu", "silu",
    "hardtanh_", "leaky_relu_", "thresholded_relu_",
    "swish", "sigmoid", "hardsigmoid", "hardswish", "hardtanh", "hardshrink",
    "softshrink", "tanhshrink", "thresholded_relu", "leaky_relu", "prelu",
    "rrelu", "log_sigmoid", "maxout", "softmax", "softmax_", "log_softmax",
    "softplus", "softsign", "tanh", "tanh_", "mish", "glu", "gumbel_softmax",
    "sigmoid_focal_loss_act",
]


def relu(x, name=None):
    return apply(jax.nn.relu, x, name="relu")


def relu_(x, name=None):
    out = relu(x)
    x._replace(out._value, out._node, out._out_idx)
    return x


def relu6(x, name=None):
    return apply(lambda a: jnp.clip(a, 0.0, 6.0), x, name="relu6")


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha=alpha), x, name="elu")


def elu_(x, alpha=1.0, name=None):
    out = elu(x, alpha)
    x._replace(out._value, out._node, out._out_idx)
    return x


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha=alpha), x, name="celu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
                 x, name="selu")


def gelu(x, approximate=False, name=None):
    return apply(lambda a: jax.nn.gelu(a, approximate=bool(approximate)),
                 x, name="gelu")


def silu(x, name=None):
    return apply(jax.nn.silu, x, name="silu")


def swish(x, name=None):
    return apply(jax.nn.silu, x, name="swish")


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, x, name="sigmoid")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x, name="hardsigmoid")


def hardswish(x, name=None):
    return apply(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x, name="hardswish")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), x, name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x,
                 name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold, 0.0)),
                 x, name="softshrink")


def tanhshrink(x, name=None):
    return apply(lambda a: a - jnp.tanh(a), x, name="tanhshrink")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(lambda a: jnp.where(a > threshold, a, value), x,
                 name="thresholded_relu")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope=negative_slope),
                 x, name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        shape = [1] * a.ndim
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return apply(fn, x, weight, name="prelu")


def rrelu(x, lower=0.125, upper=0.3333333, training=False, name=None):
    from ..._core.state import prng
    if training:
        key = prng.next_key()
        def fn(a):
            slope = jax.random.uniform(key, a.shape, jnp.float32, lower, upper)
            return jnp.where(a >= 0, a, slope.astype(a.dtype) * a)
        return apply(fn, x, name="rrelu")
    mid = (lower + upper) / 2.0
    return apply(lambda a: jnp.where(a >= 0, a, mid * a), x, name="rrelu")


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, x, name="log_sigmoid")


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply(fn, x, name="maxout")


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            from ..._core import dtypes as _dt
            a = a.astype(_dt.convert_dtype(dtype))
        return jax.nn.softmax(a, axis=int(axis))
    return apply(fn, x, name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    x._replace(out._value, out._node, out._out_idx)
    return x


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            from ..._core import dtypes as _dt
            a = a.astype(_dt.convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=int(axis))
    return apply(fn, x, name="log_softmax")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(lambda a: jnp.where(a * beta > threshold, a,
                                     jnp.log1p(jnp.exp(beta * a)) / beta),
                 x, name="softplus")


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, x, name="softsign")


def tanh(x, name=None):
    return apply(jnp.tanh, x, name="tanh")


def tanh_(x, name=None):
    out = tanh(x)
    x._replace(out._value, out._node, out._out_idx)
    return x


def mish(x, name=None):
    return apply(lambda a: a * jnp.tanh(jax.nn.softplus(a)), x, name="mish")


def glu(x, axis=-1, name=None):
    def fn(a):
        a1, a2 = jnp.split(a, 2, axis=int(axis))
        return a1 * jax.nn.sigmoid(a2)
    return apply(fn, x, name="glu")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ..._core.state import prng
    key = prng.next_key()
    def fn(a):
        g = jax.random.gumbel(key, a.shape, a.dtype if
                              jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False) \
                if hasattr(jnp, "put_along_axis") else \
                y_hard.at[jnp.broadcast_to(idx, y.shape) ==
                          jnp.arange(y.shape[axis]).reshape(
                              [-1 if i == axis % y.ndim else 1 for i in range(y.ndim)])].set(1.0)
            onehot = jax.nn.one_hot(jnp.squeeze(idx, axis), y.shape[axis], axis=axis,
                                    dtype=y.dtype)
            return onehot + jax.lax.stop_gradient(-y) + y
        return y
    return apply(fn, x, name="gumbel_softmax")


def sigmoid_focal_loss_act(x):
    return sigmoid(x)


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    """Inplace hardtanh (reference nn/functional/activation.py)."""
    from ...tensor.extras import inplace_apply
    return inplace_apply(x, lambda t: hardtanh(t, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None):
    from ...tensor.extras import inplace_apply
    return inplace_apply(x, lambda t: leaky_relu(t, negative_slope))


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    from ...tensor.extras import inplace_apply
    return inplace_apply(x, lambda t: thresholded_relu(t, threshold, value))
