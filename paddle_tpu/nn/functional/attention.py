"""Attention functionals (reference: python/paddle/nn/functional/
flash_attention.py, scaled_dot_product_attention)."""
from __future__ import annotations

import contextlib
import math

import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, apply, unwrap
from ...ops.flash_attention import flash_attention as _flash_fn


@contextlib.contextmanager
def sdp_kernel(enable_flash=True, enable_math=True, enable_mem_efficient=True):
    yield


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """q,k,v: (B, S, H, D) paddle layout. Uses pallas flash attention when
    no explicit mask is given; masked path is a fused XLA softmax graph.
    """
    if attn_mask is None:
        def fn(q, k, v):
            out, _ = _flash_fn(q, k, v, dropout=dropout_p,
                                            causal=is_causal, training=training)
            return out
        return apply(fn, query, key, value, name="scaled_dot_product_attention")

    def fn(q, k, v, m):
        qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
        kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
        vh = jnp.swapaxes(v, 1, 2)
        hq, hk = qh.shape[1], kh.shape[1]
        if hk != hq:
            kh = jnp.repeat(kh, hq // hk, axis=1)
            vh = jnp.repeat(vh, hq // hk, axis=1)
        scale = 1.0 / math.sqrt(qh.shape[-1])
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
        if m.dtype == jnp.bool_:
            s = jnp.where(m, s, -1e30)
        else:
            s = s + m.astype(jnp.float32)
        if is_causal:
            sq, sk = s.shape[-2], s.shape[-1]
            cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            s = jnp.where(cm, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vh.astype(jnp.float32))
        return jnp.swapaxes(o, 1, 2).astype(q.dtype)
    return apply(fn, query, key, value, attn_mask,
                 name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    def fn(q, k, v):
        out, _ = _flash_fn(q, k, v, dropout=dropout, causal=causal,
                                        training=training)
        return out
    out = apply(fn, query, key, value, name="flash_attention")
    return (out, None)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False, return_softmax=False,
                        training=True, name=None):
    """Varlen flash attention over packed (total, H, D) tensors
    (reference: python/paddle/nn/functional/flash_attention.py:756).
    Backed by the segment-id pallas kernel in ops/varlen_attention.py."""
    from ...ops.varlen_attention import flash_attn_unpadded as _unpadded

    def fn(q, k, v):
        out, _ = _unpadded(q, k, v, unwrap(cu_seqlens_q),
                           unwrap(cu_seqlens_k), max_seqlen_q, max_seqlen_k,
                           scale=scale, dropout=dropout, causal=causal,
                           training=training)
        return out
    out = apply(fn, query, key, value, name="flash_attn_unpadded")
    return (out, None)
