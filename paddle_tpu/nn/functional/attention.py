"""Attention functionals (reference: python/paddle/nn/functional/
flash_attention.py, scaled_dot_product_attention)."""
from __future__ import annotations

import contextlib
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, apply, unwrap
from ...ops.flash_attention import flash_attention as _flash_fn


@contextlib.contextmanager
def sdp_kernel(enable_flash=True, enable_math=True, enable_mem_efficient=True):
    yield


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """q,k,v: (B, S, H, D) paddle layout. Uses pallas flash attention when
    no explicit mask is given; masked path is a fused XLA softmax graph.
    """
    if attn_mask is None:
        def fn(q, k, v):
            out, _ = _flash_fn(q, k, v, dropout=dropout_p,
                                            causal=is_causal, training=training)
            return out
        return apply(fn, query, key, value, name="scaled_dot_product_attention")

    def fn(q, k, v, m):
        qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
        kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
        vh = jnp.swapaxes(v, 1, 2)
        hq, hk = qh.shape[1], kh.shape[1]
        if hk != hq:
            kh = jnp.repeat(kh, hq // hk, axis=1)
            vh = jnp.repeat(vh, hq // hk, axis=1)
        scale = 1.0 / math.sqrt(qh.shape[-1])
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
        if m.dtype == jnp.bool_:
            s = jnp.where(m, s, -1e30)
        else:
            s = s + m.astype(jnp.float32)
        if is_causal:
            sq, sk = s.shape[-2], s.shape[-1]
            cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            s = jnp.where(cm, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vh.astype(jnp.float32))
        return jnp.swapaxes(o, 1, 2).astype(q.dtype)
    return apply(fn, query, key, value, attn_mask,
                 name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    def fn(q, k, v):
        out, _ = _flash_fn(q, k, v, dropout=dropout, causal=causal,
                                        training=training)
        return out
    out = apply(fn, query, key, value, name="flash_attention")
    return (out, None)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False, return_softmax=False,
                        training=True, name=None):
    """Varlen flash attention over packed (total, H, D) tensors
    (reference: python/paddle/nn/functional/flash_attention.py:756).
    Backed by the segment-id pallas kernel in ops/varlen_attention.py."""
    from ...ops.varlen_attention import flash_attn_unpadded as _unpadded

    def fn(q, k, v):
        out, _ = _unpadded(q, k, v, unwrap(cu_seqlens_q),
                           unwrap(cu_seqlens_k), max_seqlen_q, max_seqlen_k,
                           scale=scale, dropout=dropout, causal=causal,
                           training=training)
        return out
    out = apply(fn, query, key, value, name="flash_attn_unpadded")
    return (out, None)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         fixed_seed_offset=None, rng_name="", training=True,
                         name=None):
    """Packed-QKV flash attention (reference flash_attention.py:562).
    qkv: (B, S, H/Hk + 2, Hk, D) — leading groups are query heads, the
    last two are K and V."""
    def fn(p):
        b, s, gp2, hk, d = p.shape
        q = p[:, :, :-2].reshape(b, s, (gp2 - 2) * hk, d)
        k = p[:, :, -2]
        v = p[:, :, -1]
        from ...ops.flash_attention import flash_attention as _flash
        out, _ = _flash(q, k, v, dropout=dropout, causal=causal,
                        training=training)
        return out
    out = apply(fn, qkv, name="flash_attn_qkvpacked")
    return (out, None)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q=None, max_seqlen_k=None,
                                scale=None, dropout=0.0, causal=False,
                                return_softmax=False, fixed_seed_offset=None,
                                rng_name="", varlen_padded=True, training=True,
                                name=None):
    """Varlen packed-QKV flash attention (reference flash_attention.py:
    flash_attn_varlen_qkvpacked). qkv: (total, H/Hk + 2, Hk, D)."""
    from ...ops.varlen_attention import flash_attn_unpadded as _unpadded

    def fn(p):
        t, gp2, hk, d = p.shape
        q = p[:, :-2].reshape(t, (gp2 - 2) * hk, d)
        k = p[:, -2]
        v = p[:, -1]
        out, _ = _unpadded(q, k, v, unwrap(cu_seqlens_q),
                           unwrap(cu_seqlens_k), max_seqlen_q, max_seqlen_k,
                           scale=scale, dropout=dropout, causal=causal,
                           training=training)
        return out
    out = apply(fn, qkv, name="flash_attn_varlen_qkvpacked")
    return (out, None)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None,
                     max_nnz=None):
    """Sparse attention with a per-row CSR layout (reference
    nn/functional/sparse_attention.py:22 — CUDA-only there; here an XLA
    gather formulation: each query row attends only to its CSR columns).

    query/key/value: (B, H, S, D); sparse_csr_offset: (B, H, S+1) int32;
    sparse_csr_columns: (B, H, nnz) int32.

    jit-compatible: only the per-row gather WIDTH must be static. With
    concrete offsets it is derived (max row nnz); under tracing pass
    `max_nnz` explicitly (an upper bound is fine — padding lanes are
    masked).
    """
    off_c = unwrap(sparse_csr_offset)
    if not isinstance(off_c, jax.core.Tracer):
        row_nnz_np = np.diff(np.asarray(off_c), axis=-1)
        derived = int(row_nnz_np.max()) if row_nnz_np.size else 0
        if max_nnz is None:
            max_nnz = derived
        elif max_nnz < derived:
            # a too-small width would silently drop keys from the
            # softmax — validation is free while offsets are concrete
            raise ValueError(
                f"max_nnz={max_nnz} is smaller than the widest CSR "
                f"row ({derived} columns): attention would be "
                "silently truncated")
    elif max_nnz is None:
        raise ValueError(
            "sparse_attention under jit needs a static max_nnz= "
            "(the widest row's nonzero count, or any upper bound)")

    def fn(q, k, v, off, cols, *rest):
        rest = list(rest)
        kpm = rest.pop(0) if key_padding_mask is not None else None
        am = rest.pop(0) if attn_mask is not None else None
        d = q.shape[-1]
        b_, h_, s_ = off.shape[0], off.shape[1], off.shape[2] - 1
        row_nnz = jnp.diff(off, axis=-1)                   # (B, H, S)
        lane = jnp.arange(max_nnz)
        base = off[..., :-1, None] + lane                  # (B, H, S, n)
        mask = lane < row_nnz[..., None]
        base = jnp.where(mask, base, 0)
        gi = jnp.take_along_axis(
            jnp.broadcast_to(cols[..., None, :],
                             cols.shape[:2] + (s_, cols.shape[-1])),
            base, axis=-1)                                 # col ids
        kg = jnp.take_along_axis(k[:, :, None], gi[..., None], axis=3)
        vg = jnp.take_along_axis(v[:, :, None], gi[..., None], axis=3)
        scores = jnp.einsum("bhsd,bhsnd->bhsn", q.astype(jnp.float32),
                            kg.astype(jnp.float32)) / math.sqrt(d)
        if kpm is not None:  # (B, S_k): 0/-inf style or bool keep-mask
            keep = jnp.take_along_axis(
                jnp.broadcast_to(kpm[:, None, None, :],
                                 (b_, h_, s_, kpm.shape[-1])), gi, axis=-1)
            mask = mask & (keep > -1.0) if keep.dtype != jnp.bool_ else \
                mask & keep
        scores = jnp.where(mask, scores, -jnp.inf)
        if am is not None:   # dense (B, H, S, S_k) additive mask
            scores = scores + jnp.take_along_axis(am.astype(jnp.float32),
                                                  gi, axis=-1)
        p = jax.nn.softmax(scores, axis=-1)
        p = jnp.where(mask, p, 0.0)
        out = jnp.einsum("bhsn,bhsnd->bhsd", p, vg.astype(jnp.float32))
        return out.astype(q.dtype)

    args = [query, key, value, sparse_csr_offset, sparse_csr_columns]
    if key_padding_mask is not None:
        args.append(key_padding_mask)
    if attn_mask is not None:
        args.append(attn_mask)
    return apply(fn, *args, name="sparse_attention")


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, return_seed_offset=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """FlashMask attention (reference flash_attention.py:1299): sparse
    causal masks expressed as per-column start/end row indices instead of
    a dense (S, S) mask.

    Routing: no indices/window → plain flash attention (pallas on TPU).
    With indices/window → the FlashMask pallas kernel
    (ops/flashmask_attention.py): start/end columns streamed
    block-by-block, fully-masked blocks skipped, O(S·block) memory —
    the kernel path never materializes a dense (S, S) mask for any
    config, dropout included (training-time dropout is applied
    IN-KERNEL from a deterministic counter-based mask, matching the
    reference CUDA kernel's philox attention-probability dropout).
    Off-TPU the dense flashmask_reference still runs — correctness
    baseline, not the memory-scaling path.

    startend_row_indices: (B, Hk, S_k, {1, 2, 4}) int32 — see the
    reference docstring for the per-shape semantics (LT start / LT
    start+end / LT start + UT end / LT+UT start+end). Invalid
    (causal, n) combinations raise ValueError on both paths.
    """
    if startend_row_indices is None and window_size is None:
        return flash_attention(query, key, value, dropout=dropout,
                               causal=causal, training=training)

    from ...ops.flashmask_attention import flashmask_attention_bhsd
    use_dropout = dropout > 0.0 and training
    # seed drawn OUTSIDE fn: tape backward re-executes fn via jax.vjp,
    # and an in-fn next_key() would give the backward a different
    # dropout mask than the forward (see _dropout_impl in common.py).
    # The kernel regenerates its mask from (seed, coords), so the seed
    # is the only state to thread.
    dropout_seed = None
    if use_dropout:
        from ..._core.state import prng
        dropout_seed = jax.random.randint(prng.next_key(), (), 0,
                                          jnp.iinfo(jnp.int32).max,
                                          jnp.int32)

    def fn(q, k, v, *rest):
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        h = qh.shape[1]
        if kh.shape[1] != h:
            kh = jnp.repeat(kh, h // kh.shape[1], axis=1)
            vh = jnp.repeat(vh, h // vh.shape[1], axis=1)
        sri = None
        if rest:
            sri = rest[0].astype(jnp.int32)
            if sri.shape[1] != h:
                sri = jnp.repeat(sri, h // sri.shape[1], axis=1)
        out = flashmask_attention_bhsd(
            qh, kh, vh, sri, causal=causal, window=window_size,
            dropout=dropout if use_dropout else 0.0,
            dropout_seed=dropout_seed)
        return jnp.swapaxes(out, 1, 2).astype(q.dtype)

    args = [query, key, value]
    if startend_row_indices is not None:
        args.append(startend_row_indices)
    return apply(fn, *args, name="flashmask_attention")
