"""Common functionals: linear/dropout/pad/interpolate/embedding/etc.
(reference: python/paddle/nn/functional/common.py, input.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..._core import dtypes as _dt
from ..._core.state import prng
from ..._core.tensor import Tensor, apply, unwrap

__all__ = [
    "linear", "bilinear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "feature_alpha_dropout", "pad", "zeropad2d", "cosine_similarity",
    "pairwise_distance", "interpolate", "upsample", "one_hot", "embedding",
    "label_smooth", "unfold", "fold", "class_center_sample",
]


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b. W stored (in, out) → direct MXU dot, no transpose."""
    if bias is not None:
        return apply(lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias,
                     name="linear")
    return apply(jnp.matmul, x, weight, name="linear")


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, bb=None):
        # w: (out, in1, in2)
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        return out + bb if bb is not None else out
    if bias is not None:
        return apply(fn, x1, x2, weight, bias, name="bilinear")
    return apply(fn, x1, x2, weight, name="bilinear")


def _dropout_impl(x, p, training, mode, broadcast_dims=None, name="dropout"):
    if not training or p == 0.0:
        return x.clone() if isinstance(x, Tensor) else x
    if p == 1.0:
        return apply(lambda a: jnp.zeros_like(a) if mode == "upscale_in_train"
                     else jnp.zeros_like(a), x, name=name)
    key = prng.next_key()

    def fn(a):
        shape = list(a.shape)
        if broadcast_dims:
            for d in broadcast_dims:
                shape[d] = 1
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))
        return jnp.where(keep, a, jnp.zeros((), a.dtype))
    return apply(fn, x, name=name)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    bdims = None
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        bdims = [d for d in range(x.ndim) if d not in [a % x.ndim for a in axes]]
    return _dropout_impl(x, float(p), training, mode, broadcast_dims=bdims)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    bdims = [2, 3] if data_format == "NCHW" else [1, 2]
    return _dropout_impl(x, float(p), training, "upscale_in_train",
                         broadcast_dims=bdims, name="dropout2d")


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    bdims = [2, 3, 4] if data_format == "NCDHW" else [1, 2, 3]
    return _dropout_impl(x, float(p), training, "upscale_in_train",
                         broadcast_dims=bdims, name="dropout3d")


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x.clone()
    key = prng.next_key()

    def fn(a):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, jnp.asarray(alpha_p, a.dtype)) + b_coef
    return apply(fn, x, name="alpha_dropout")


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    return alpha_dropout(x, p, training)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True,
        name=None):
    pad_list = [int(unwrap(p)) for p in (pad.tolist() if isinstance(pad, Tensor) else pad)] \
        if not isinstance(pad, int) else [int(pad)]

    def fn(a):
        nd = a.ndim
        if len(pad_list) == 2 * nd:
            if pad_from_left_axis:
                widths = [(pad_list[2 * i], pad_list[2 * i + 1]) for i in range(nd)]
            else:
                widths = [(pad_list[2 * (nd - 1 - i)], pad_list[2 * (nd - 1 - i) + 1])
                          for i in range(nd)]
        else:
            # paddle convention: pad applies to last-k spatial dims per data_format
            k = len(pad_list) // 2
            widths = [(0, 0)] * nd
            if data_format.endswith("C") and nd >= 3:  # NLC/NHWC/NDHWC
                spatial = list(range(1, 1 + k))
            else:
                spatial = list(range(nd - k, nd))
            for j, d in enumerate(spatial):
                widths[d] = (pad_list[2 * j], pad_list[2 * j + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, widths, mode="constant",
                           constant_values=jnp.asarray(value, a.dtype))
        return jnp.pad(a, widths, mode=jmode)
    return apply(fn, x, name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format,
               pad_from_left_axis=False)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        d1 = jnp.sqrt(jnp.sum(a * a, axis=axis))
        d2 = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(d1 * d2, eps)
    return apply(fn, x1, x2, name="cosine_similarity")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = a - b + epsilon
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p), axis=-1, keepdims=keepdim),
                         1.0 / p)
    return apply(fn, x, y, name="pairwise_distance")


def one_hot(x, num_classes, name=None):
    return apply(lambda a: jax.nn.one_hot(a, int(num_classes),
                                          dtype=_dt.get_default_dtype()),
                 x, name="one_hot")


def embedding(x, weight, padding_idx=None, max_norm=None, norm_type=2.0,
              sparse=False, scale_grad_by_freq=False, name=None):
    def fn(idx, w):
        if max_norm is not None:
            norms = jnp.linalg.norm(w, ord=norm_type, axis=-1, keepdims=True)
            w = w * jnp.minimum(1.0, max_norm / (norms + 1e-7))
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out
    return apply(fn, x, weight, name="embedding")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l, pd=None):
        k = l.shape[-1]
        uniform = pd if pd is not None else jnp.full((k,), 1.0 / k, l.dtype)
        return (1.0 - epsilon) * l + epsilon * uniform
    if prior_dist is not None:
        return apply(fn, label, prior_dist, name="label_smooth")
    return apply(fn, label, name="label_smooth")


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (NCHW in/out like reference)."""
    k = _pair(kernel_sizes)
    s = _pair(strides)
    d = _pair(dilations)
    p = _pair(paddings, 4) if isinstance(paddings, (list, tuple)) and len(paddings) == 4 \
        else _pair(paddings) * 2

    def fn(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, ((0, 0), (0, 0), (p[0], p[2] if len(p) == 4 else p[0]),
                          (p[1], p[3] if len(p) == 4 else p[1])))
        hp = a_p.shape[2]
        wp = a_p.shape[3]
        oh = (hp - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (wp - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        patches = jax.lax.conv_general_dilated_patches(
            a_p, filter_shape=k, window_strides=s, padding="VALID",
            rhs_dilation=d, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: (n, c*k0*k1, oh, ow)
        return patches.reshape(n, c * k[0] * k[1], oh * ow)
    return apply(fn, x, name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    out_hw = _pair(output_sizes)
    k = _pair(kernel_sizes)
    s = _pair(strides)
    d = _pair(dilations)
    p = _pair(paddings)

    def fn(a):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        oh = (out_hw[0] + 2 * p[0] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (out_hw[1] + 2 * p[1] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        a_r = a.reshape(n, c, k[0], k[1], oh, ow)
        hp, wp = out_hw[0] + 2 * p[0], out_hw[1] + 2 * p[1]
        out = jnp.zeros((n, c, hp, wp), a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                hi = i * d[0]
                wi = j * d[1]
                out = out.at[:, :, hi:hi + oh * s[0]:s[0], wi:wi + ow * s[1]:s[1]].add(
                    a_r[:, :, i, j])
        return out[:, :, p[0]:hp - p[0], p[1]:wp - p[1]]
    return apply(fn, x, name="fold")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format=None, name=None):
    if data_format is None:
        data_format = {3: "NCW", 4: "NCHW", 5: "NCDHW"}[x.ndim]
    channel_last = data_format[-1] == "C"
    nsp = x.ndim - 2

    if size is not None:
        if isinstance(size, Tensor):
            size = [int(v) for v in np.asarray(size._value)]
        out_sp = [int(unwrap(s)) for s in (size if isinstance(size, (list, tuple)) else [size])]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nsp
        in_sp = x.shape[1:-1] if channel_last else x.shape[2:]
        out_sp = [int(s * float(unwrap(f))) for s, f in zip(in_sp, sf)]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def fn(a):
        if channel_last:
            tgt = (a.shape[0],) + tuple(out_sp) + (a.shape[-1],)
        else:
            tgt = a.shape[:2] + tuple(out_sp)
        if mode == "nearest":
            return jax.image.resize(a, tgt, method="nearest")
        if align_corners:
            # jax.image.resize has no align_corners; emulate via explicit grid
            sp_axes = builtins_range(1, 1 + nsp) if channel_last else builtins_range(2, 2 + nsp)
            out = a
            for ax, o in zip(sp_axes, out_sp):
                n_in = out.shape[ax]
                if o == 1 or n_in == 1:
                    idx = jnp.zeros((o,), jnp.float32)
                else:
                    idx = jnp.linspace(0.0, n_in - 1.0, o)
                lo = jnp.floor(idx).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, n_in - 1)
                wgt = (idx - lo).astype(a.dtype)
                sl_lo = jnp.take(out, lo, axis=ax)
                sl_hi = jnp.take(out, hi, axis=ax)
                shape = [1] * out.ndim
                shape[ax] = o
                w = wgt.reshape(shape)
                out = sl_lo * (1 - w) + sl_hi * w
            return out
        return jax.image.resize(a, tgt, method=jmode)
    builtins_range = range
    return apply(fn, x, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format=None, name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def class_center_sample(label, num_classes, num_samples, group=None):
    lab = np.asarray(unwrap(label))
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos[:num_samples]
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        extra = rest[: num_samples - len(pos)]
        sampled = np.concatenate([pos, extra])
    remap = -np.ones(num_classes, dtype=np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(jnp.asarray(remap[lab])), Tensor(jnp.asarray(sampled)))
