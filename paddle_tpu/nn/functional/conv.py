"""Convolution functionals (reference: python/paddle/nn/functional/conv.py).

TPU-native design: all convs lower to a single lax.conv_general_dilated
with explicit dimension_numbers — XLA:TPU tiles these onto the MXU.
Kernel storage layout is (*spatial, in/groups, out) (HWIO-style), the
layout XLA prefers; NCHW/NHWC input is handled by dimension numbers, not
transposes.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..._core.tensor import apply, unwrap

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
           "conv3d_transpose"]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        return tuple(int(x) for x in v) * (n // len(v))
    return (int(v),) * n


def _dim_numbers(nsp, channel_last):
    if nsp == 1:
        lhs = "NWC" if channel_last else "NCW"
        out = lhs
        rhs = "WIO"
    elif nsp == 2:
        lhs = "NHWC" if channel_last else "NCHW"
        out = lhs
        rhs = "HWIO"
    else:
        lhs = "NDHWC" if channel_last else "NCDHW"
        out = lhs
        rhs = "DHWIO"
    return (lhs, rhs, out)


def _padding_arg(padding, nsp, channel_last):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * nsp
    padding = [int(unwrap(p)) for p in padding]
    if len(padding) == nsp:
        return [(p, p) for p in padding]
    if len(padding) == 2 * nsp:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nsp)]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, nsp, name):
    channel_last = data_format[-1] == "C"
    dn = _dim_numbers(nsp, channel_last)
    s = _tuple(stride, nsp)
    d = _tuple(dilation, nsp)
    pad_arg = _padding_arg(padding, nsp, channel_last)

    def fn(a, w, b=None):
        out = lax.conv_general_dilated(
            a, w, window_strides=s, padding=pad_arg, rhs_dilation=d,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=None)
        if b is not None:
            bshape = [1] * out.ndim
            bshape[out.ndim - 1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(bshape)
        return out
    if bias is not None:
        return apply(fn, x, weight, bias, name=name)
    return apply(fn, x, weight, name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, fmt, 1, "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2,
                 "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3,
                 "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, data_format, nsp, output_size, name):
    channel_last = data_format[-1] == "C"
    dn = _dim_numbers(nsp, channel_last)
    s = _tuple(stride, nsp)
    d = _tuple(dilation, nsp)
    op = _tuple(output_padding, nsp) if output_padding is not None else (0,) * nsp

    if isinstance(padding, str):
        pad_pairs = None
        pad_str = padding.upper()
    else:
        pad_str = None
        pad_pairs = _padding_arg(padding, nsp, channel_last)

    def fn_with_flip(a, w, b=None):
        # transposed conv = conv with lhs_dilation + spatially-flipped kernel,
        # with in/out swapped: w stored (*spatial, out_c, in_c/groups)
        wf = jnp.flip(w, axis=tuple(range(nsp)))
        wf = jnp.swapaxes(wf, -1, -2)  # → (*spatial, in/groups, out)
        k = w.shape[:nsp]
        if pad_pairs is not None:
            pads = []
            for i in range(nsp):
                eff_k = d[i] * (k[i] - 1) + 1
                lo = eff_k - 1 - pad_pairs[i][0]
                hi = eff_k - 1 - pad_pairs[i][1] + op[i]
                pads.append((lo, hi))
        else:
            if pad_str == "VALID":
                pads = [(d[i] * (k[i] - 1), d[i] * (k[i] - 1) + op[i]) for i in range(nsp)]
            else:  # SAME
                pads = []
                for i in range(nsp):
                    eff_k = d[i] * (k[i] - 1) + 1
                    total = eff_k - s[i] if eff_k > s[i] else 0
                    lo = eff_k - 1 - total // 2
                    hi = eff_k - 1 - (total - total // 2) + op[i]
                    pads.append((lo, hi))
        out = lax.conv_general_dilated(
            a, wf, window_strides=(1,) * nsp, padding=pads, lhs_dilation=s,
            rhs_dilation=d, dimension_numbers=dn, feature_group_count=groups)
        if b is not None:
            bshape = [1] * out.ndim
            bshape[out.ndim - 1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(bshape)
        return out

    if bias is not None:
        return apply(fn_with_flip, x, weight, bias, name=name)
    return apply(fn_with_flip, x, weight, name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL",
                     name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, fmt, 1, output_size, "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, data_format, 2, output_size, "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, data_format, 3, output_size, "conv3d_transpose")
