"""Extension functionals (reference: python/paddle/nn/functional/extension.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..._core import dtypes as _dt
from ..._core.tensor import apply, unwrap

__all__ = ["sequence_mask", "temporal_shift", "diag_embed", "gather_tree"]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    ml = int(unwrap(maxlen)) if maxlen is not None else \
        int(np.asarray(unwrap(x)).max())
    d = _dt.convert_dtype(dtype)
    return apply(lambda a: (jnp.arange(ml) < a[..., None]).astype(d), x,
                 name="sequence_mask")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(a):
        if data_format == "NHWC":
            a = jnp.moveaxis(a, -1, 1)
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], 1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                                 v[:, :-1, fold:2 * fold]], 1)
        mid = v[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, mid], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply(fn, x, name="temporal_shift")


from ...tensor.creation import diag_embed  # noqa: E402,F401


def gather_tree(ids, parents):
    def fn(idv, par):
        T, B, W = idv.shape

        def step(carry, t):
            beams = carry  # (B, W) current beam indices
            tok = jnp.take_along_axis(idv[t], beams, axis=1)
            newbeams = jnp.take_along_axis(par[t], beams, axis=1)
            return newbeams, tok

        last = jnp.broadcast_to(jnp.arange(W)[None, :], (B, W))
        _, toks = jax.lax.scan(step, last, jnp.arange(T - 1, -1, -1))
        return jnp.flip(toks, axis=0)
    return apply(fn, ids, parents, name="gather_tree")
