"""Loss functionals (reference: python/paddle/nn/functional/loss.py).

cross_entropy fuses log_softmax+gather into one XLA graph (the
reference's softmax_with_cross_entropy fused CUDA kernel is just the
natural lowering here).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, apply, unwrap

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "square_error_cost",
    "mse_loss", "l1_loss", "smooth_l1_loss", "nll_loss", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "kl_div", "margin_ranking_loss",
    "hinge_embedding_loss", "cosine_embedding_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "ctc_loss", "huber_loss",
    "poisson_nll_loss", "gaussian_nll_loss", "sigmoid_focal_loss", "dice_loss",
    "log_loss", "npair_loss", "multi_label_soft_margin_loss", "soft_margin_loss",
    "multi_margin_loss", "margin_cross_entropy", "rnnt_loss", "adaptive_log_softmax_with_loss", "hsigmoid_loss",
]


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    def fn(logits, lab, w=None):
        ax = axis % logits.ndim
        nclass = logits.shape[ax]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=ax) if use_softmax \
            else jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape):
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0.0:
                soft = (1 - label_smoothing) * soft + label_smoothing / nclass
            loss = -jnp.sum(soft * logp, axis=ax)
            if w is not None:
                wt = jnp.sum(soft * w.reshape((-1,) if ax == logits.ndim - 1 else None),
                             axis=ax)
                loss = loss * wt
            return _reduce(loss, reduction)
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logits.ndim:  # trailing 1 dim
            lab_i = jnp.squeeze(lab_i, axis=ax)
        valid = lab_i != ignore_index
        safe_lab = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe_lab, ax), axis=ax)
        picked = jnp.squeeze(picked, axis=ax)
        if label_smoothing > 0.0:
            loss = -((1 - label_smoothing) * picked +
                     label_smoothing * jnp.mean(logp, axis=ax))
        else:
            loss = -picked
        loss = jnp.where(valid, loss, 0.0)
        if w is not None:
            wt = jnp.where(valid, jnp.take(w, safe_lab), 0.0)
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
        if reduction == "mean":
            cnt = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(loss) / cnt
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply(fn, *args, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax
    loss = apply(lambda l: jnp.expand_dims(l, axis), loss, name="unsqueeze_loss")
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), input, label, name="square_error_cost")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction), input, label,
                 name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label,
                 name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle's smooth_l1_loss multiplies by delta
        return _reduce(loss * delta, reduction)
    return apply(fn, input, label, name="smooth_l1_loss")


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply(fn, input, label, name="huber_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def fn(logp, lab, w=None):
        ax = 1 if logp.ndim > 1 else 0
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, ax), axis=ax)
        loss = -jnp.squeeze(picked, axis=ax)
        wt = jnp.take(w, safe) if w is not None else jnp.ones_like(loss)
        wt = jnp.where(valid, wt, 0.0)
        loss = loss * wt
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply(fn, *args, name="nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, t, w=None):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply(fn, *args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def fn(z, t, *rest):
        w = rest[0] if weight is not None else None
        pw = rest[-1] if pos_weight is not None else None
        # stable: max(z,0) - z*t + log(1+exp(-|z|)), with pos_weight on positive term
        if pw is not None:
            log_sig = jax.nn.log_sigmoid(z)
            log_sig_neg = jax.nn.log_sigmoid(-z)
            loss = -(pw * t * log_sig + (1 - t) * log_sig_neg)
        else:
            loss = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply(fn, *args, name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(logp, t):
        if log_target:
            loss = jnp.exp(t) * (t - logp)
        else:
            loss = jnp.where(t > 0, t * (jnp.log(jnp.maximum(t, 1e-30)) - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply(fn, input, label, name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, t):
        return _reduce(jnp.maximum(0.0, -t * (a - b) + margin), reduction)
    return apply(fn, input, other, label, name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def fn(a, t):
        loss = jnp.where(t == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply(fn, input, label, name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    def fn(a, b, t):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(t == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply(fn, input1, input2, label, name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p), -1), 1 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        return _reduce(jnp.maximum(0.0, d_ap - d_an + margin), reduction)
    return apply(fn, input, positive, negative, name="triplet_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative, distance_function=None,
                                      margin=1.0, swap=False, reduction="mean",
                                      name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin, swap=swap,
                                   reduction=reduction)
    d_ap = distance_function(input, positive)
    d_an = distance_function(input, negative)
    if swap:
        d_pn = distance_function(positive, negative)
        d_an_v = apply(lambda x, y: jnp.minimum(x, y), d_an, d_pn, name="min")
    else:
        d_an_v = d_an
    return apply(lambda a, b: _reduce(jnp.maximum(0.0, a - b + margin), reduction),
                 d_ap, d_an_v, name="triplet_distance_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def fn(z, t, w=None):
        loss = -(t * jax.nn.log_sigmoid(z) + (1 - t) * jax.nn.log_sigmoid(-z))
        loss = jnp.mean(loss, axis=-1)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply(fn, *args, name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    return apply(lambda z, t: _reduce(jnp.log1p(jnp.exp(-t * z)), reduction),
                 input, label, name="soft_margin_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None, reduction="mean",
                      name=None):
    def fn(z, t, w=None):
        n, c = z.shape
        correct = jnp.take_along_axis(z, t[:, None].astype(jnp.int32), axis=1)
        diff = jnp.maximum(0.0, margin - correct + z)
        diff = jnp.power(diff, p)
        if w is not None:
            diff = diff * jnp.take(w, t.astype(jnp.int32))[:, None]
        mask = jax.nn.one_hot(t.astype(jnp.int32), c, dtype=z.dtype)
        loss = jnp.sum(diff * (1 - mask), axis=1) / c
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply(fn, *args, name="multi_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC via optax-style forward algorithm (logsumexp DP over lax.scan)."""
    def fn(lp, lab, in_len, lab_len):
        # lp: (T, B, C) paddle layout
        T, B, C = lp.shape
        logp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        S = lab.shape[1]
        # extended label seq: blank, l1, blank, l2, ... blank → length 2S+1
        ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        ext_valid = jnp.arange(2 * S + 1)[None, :] < (2 * lab_len[:, None] + 1)
        neg_inf = jnp.asarray(-1e30, jnp.float32)
        alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[0, jnp.arange(B), blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, logp[0, jnp.arange(B), ext[:, 1]], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, t):
            a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2)
            emit = logp[t][jnp.arange(B)[:, None], ext]
            new_alpha = merged + emit
            new_alpha = jnp.where(t < in_len[:, None], new_alpha, alpha)
            new_alpha = jnp.where(ext_valid, new_alpha, neg_inf)
            return new_alpha, None

        alphaT, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        last = 2 * lab_len
        ll = jnp.logaddexp(
            jnp.take_along_axis(alphaT, last[:, None].astype(jnp.int32), 1)[:, 0],
            jnp.take_along_axis(alphaT, jnp.maximum(last - 1, 0)[:, None].astype(jnp.int32), 1)[:, 0])
        loss = -ll
        if norm_by_times:
            loss = loss / in_len.astype(jnp.float32)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)
    return apply(fn, log_probs, labels, input_lengths, label_lengths, name="ctc_loss")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (reference: phi warprnnt kernel wrapper).

    input: (B, Tmax, Umax+1, V) joint-network logits; label: (B, Umax).
    Forward-variable lattice DP in the log semiring via lax.scan over T
    (the in-row u-recurrence is a second scan) — static shapes, jittable.
    """
    def fn(logits, lab, t_len, u_len):
        B, T, U1, V = logits.shape
        U = U1 - 1
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        neg_inf = jnp.asarray(-1e30, jnp.float32)
        bidx = jnp.arange(B)
        # emission prob of label u at lattice node (t, u): (B, T, U)
        lab_i = lab.astype(jnp.int32)
        emit = jnp.take_along_axis(
            logp[:, :, :U], lab_i[:, None, :, None], axis=3)[..., 0]
        blank_p = logp[..., blank]                     # (B, T, U+1)
        if fastemit_lambda:
            # FastEmit (warp-transducer semantics): leave the loss VALUE
            # unchanged and scale emission-path gradients by (1+λ).
            # value: e(1+λ) − eλ = e;  grad: (1+λ)·de.
            lam = jnp.asarray(fastemit_lambda, jnp.float32)
            emit = emit * (1.0 + lam) - jax.lax.stop_gradient(emit * lam)

        u_range = jnp.arange(U1)
        u_valid = u_range[None, :] <= u_len[:, None]   # (B, U+1)

        def row_scan(a_prev, t):
            # A(u) = alpha(t-1, u) + blank(t-1, u)
            A = a_prev + blank_p[:, t - 1]
            # x_u = logaddexp(A_u, x_{u-1} + emit(t, u-1)): scan over u
            def inner(x_prev, u):
                e = jnp.where(u >= 1, emit[:, t, jnp.maximum(u - 1, 0)],
                              neg_inf)
                x = jnp.logaddexp(A[:, u], x_prev + e)
                return x, x
            x0 = jnp.full((B,), neg_inf)
            # u = 0 row: only the vertical (blank) path
            _, xs = jax.lax.scan(inner, A[:, 0], u_range[1:])
            row = jnp.concatenate([A[:, 0][None], xs], axis=0).T  # (B, U+1)
            row = jnp.where(u_valid, row, neg_inf)
            row = jnp.where((t < t_len)[:, None], row, a_prev)
            return row, None

        # t = 0 row: alpha(0, u) = sum of emits along u
        first = jnp.concatenate(
            [jnp.zeros((B, 1)), jnp.cumsum(emit[:, 0], axis=1)], axis=1)
        first = jnp.where(u_valid, first, neg_inf)
        alpha, _ = jax.lax.scan(row_scan, first, jnp.arange(1, T))
        # ll = alpha(T-1, U) + blank(T-1, U) at each sequence's true ends
        a_final = alpha[bidx, u_len]
        ll = a_final + blank_p[bidx, t_len - 1, u_len]
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss)
        return _reduce(loss, reduction)
    return apply(fn, input, label, input_lengths, label_lengths,
                 name="rnnt_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(z, t):
        if log_input:
            loss = jnp.exp(z) - t * z
        else:
            loss = z - t * jnp.log(z + epsilon)
        if full:
            stirling = t * jnp.log(t + 1e-12) - t + 0.5 * jnp.log(2 * np.pi * (t + 1e-12))
            loss = loss + jnp.where(t > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return apply(fn, input, label, name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def fn(mu, t, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + jnp.square(mu - t) / var)
        if full:
            loss = loss + 0.5 * np.log(2 * np.pi)
        return _reduce(loss, reduction)
    return apply(fn, input, label, variance, name="gaussian_nll_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, t, nrm=None):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * t + (1 - p) * (1 - t)
        mod = jnp.power(1 - p_t, gamma)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * mod * ce
        if nrm is not None:
            loss = loss / nrm
        return _reduce(loss, reduction)
    args = [logit, label]
    if normalizer is not None:
        args.append(normalizer)
    return apply(fn, *args, name="sigmoid_focal_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    def fn(p, t):
        t_oh = jax.nn.one_hot(jnp.squeeze(t, -1).astype(jnp.int32), p.shape[-1],
                              dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * t_oh, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(t_oh, axis=red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply(fn, input, label, name="dice_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply(lambda p, t: -t * jnp.log(p + epsilon) -
                 (1 - t) * jnp.log(1 - p + epsilon), input, label, name="log_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, lab):
        sim = a @ p.T
        eq = (lab[:, None] == lab[None, :]).astype(jnp.float32)
        eq = eq / jnp.sum(eq, axis=1, keepdims=True)
        xent = jnp.mean(jnp.sum(-eq * jax.nn.log_softmax(sim, axis=1), axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return xent + reg
    return apply(fn, anchor, positive, labels, name="npair_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    def fn(z, t):
        ti = t.astype(jnp.int32).reshape(-1)
        theta = jnp.arccos(jnp.clip(jnp.take_along_axis(z, ti[:, None], 1), -1, 1))
        target_logit = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(ti, z.shape[-1], dtype=z.dtype)
        new_z = scale * (z * (1 - onehot) + target_logit * onehot)
        logp = jax.nn.log_softmax(new_z, 1)
        loss = -jnp.take_along_axis(logp, ti[:, None], 1)
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jax.nn.softmax(new_z, 1)
        return loss
    if return_softmax:
        return apply(fn, logits, label, name="margin_cross_entropy", multi=True)
    return apply(fn, logits, label, name="margin_cross_entropy")


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive (hierarchical) softmax NLL — reference:
    python/paddle/nn/functional/loss.py:4458.

    cutoffs: ``[c0, c1, ..., n_classes]``; head covers the ``c0`` shortlist
    classes plus one logit per tail cluster. TPU redesign: instead of the
    reference's per-cluster index_select/scatter (dynamic shapes), every
    cluster's log-prob is computed densely for all rows and the right one
    selected by mask — static shapes, MXU-friendly, identical math.
    Returns (per-sample logprob ``output``, scalar ``loss = -mean``).
    """
    cuts = [int(c) for c in cutoffs]
    c0 = cuts[0]
    n_clusters = len(cuts) - 1

    def fn(x, lab, hw, *rest):
        bias = rest[-1] if head_bias is not None else None
        tails = rest[:2 * n_clusters]
        squeeze = x.ndim == 1
        if squeeze:
            x = x[None]
            lab = lab.reshape(1)
        lab = lab.astype(jnp.int32)
        head = x @ hw
        if bias is not None:
            head = head + bias
        head_lp = jax.nn.log_softmax(head, axis=-1)          # (B, c0+K)
        in_short = lab < c0
        out = jnp.take_along_axis(
            head_lp[:, :c0], jnp.clip(lab, 0, c0 - 1)[:, None], axis=1)[:, 0]
        out = jnp.where(in_short, out, 0.0)
        for i in range(1, n_clusters + 1):
            low, high = cuts[i - 1], cuts[i]
            w1, w2 = tails[2 * (i - 1)], tails[2 * (i - 1) + 1]
            clp = jax.nn.log_softmax((x @ w1) @ w2, axis=-1)  # (B, high-low)
            rel = jnp.clip(lab - low, 0, high - low - 1)
            local = jnp.take_along_axis(clp, rel[:, None], axis=1)[:, 0]
            in_c = (lab >= low) & (lab < high)
            out = jnp.where(in_c, head_lp[:, c0 + i - 1] + local, out)
        loss = -jnp.mean(out)
        if squeeze:
            out = out[0]
        return out, loss

    args = [input, head_weight]
    for pair in tail_weights:
        args.extend(pair)
    if head_bias is not None:
        args.append(head_bias)
    lab_raw = unwrap(label)
    try:
        lmin, lmax = int(jnp.min(lab_raw)), int(jnp.max(lab_raw))
        if lmin < 0 or lmax >= cuts[-1]:
            raise ValueError(
                f"label values should be in [0, n_classes - 1], but values "
                f"in range [{lmin}, {lmax}] were found.")
    except TypeError:
        pass  # traced labels: bounds unavailable
    return apply(lambda x, hw, *r: fn(x, unwrap(label), hw, *r), input,
                 *args[1:], name="adaptive_log_softmax_with_loss", multi=True)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference nn/functional/loss.py:926; phi
    hsigmoid_loss_kernel + matrix_bit_code SimpleCode/CustomCode).

    Default tree: class c encodes as ``c + num_classes`` in a complete
    binary tree with root id 1; weight row for bit j is the encoding
    prefix ``(c >> (j+1)) - 1``, the binary target is suffix bit
    ``(c >> j) & 1``. Matches the reference numerics exactly, including
    its out-of-path log(2) padding terms (same constant appears in its
    forward; gradients are unaffected). is_sparse is accepted for API
    parity — on TPU dense gather/scatter IS the fast path.
    """
    nm1 = num_classes - 1

    def fn(x, lab, w, *rest):
        rest = list(rest)
        b = rest.pop(0) if bias is not None else None
        ptab = rest.pop(0) if path_table is not None else None
        pcode = rest.pop(0) if path_code is not None else None
        lab = lab.reshape(-1).astype(jnp.int64)
        if ptab is None:
            code_length = int(num_classes - 1).bit_length()
            c = lab + num_classes
            js = jnp.arange(code_length, dtype=jnp.int64)
            valid = (c[:, None] >> (js[None, :] + 1)) > 0
            idx = jnp.clip((c[:, None] >> (js[None, :] + 1)) - 1, 0, nm1 - 1)
            bit = ((c[:, None] >> js[None, :]) & 1).astype(x.dtype)
        else:
            ptab = ptab.astype(jnp.int64)
            valid = ptab >= 0
            idx = jnp.clip(ptab, 0, nm1 - 1)
            bit = pcode.astype(x.dtype) * valid
        pre = jnp.einsum("nd,nld->nl", x.astype(jnp.float32),
                         w[idx].astype(jnp.float32))
        if b is not None:
            pre = pre + b.reshape(-1)[idx]
        pre = jnp.clip(pre, -40.0, 40.0)
        pre = jnp.where(valid, pre, 0.0)
        bit = jnp.where(valid, bit.astype(jnp.float32), 0.0)
        loss = jnp.sum(jnp.log1p(jnp.exp(pre)) - bit * pre, axis=1,
                       keepdims=True)
        return loss.astype(x.dtype)

    args = [input, label, weight]
    if bias is not None:
        args.append(bias)
    if path_table is not None:
        args.append(path_table)
    if path_code is not None:
        args.append(path_code)
    return apply(fn, *args, name="hsigmoid_loss")
