"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

All norms are expressed as fusable jnp graphs; XLA fuses
mean/var/rsqrt/scale into one or two HBM passes on TPU (what the
reference needs hand-written phi kernels for). SyncBatchNorm's
cross-device reduction uses psum over the data-parallel mesh axis.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, apply, unwrap

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "local_response_norm", "normalize", "rms_norm"]


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None,
               sync_axis=None, name=None):
    """Functional BN. In training mode updates running stats in-place
    (imperative parity); compiled training uses Layer's functional path.
    sync_axis: mesh axis name for SyncBatchNorm psum (tpu-native).
    """
    channel_last = data_format[-1] == "C" and len(data_format) > 2
    if use_global_stats is None:
        use_global_stats = not training

    ch_axis = (x.ndim - 1) if channel_last else (1 if x.ndim > 1 else 0)
    red_axes = tuple(i for i in range(x.ndim) if i != ch_axis)

    def bshape(ndim):
        s = [1] * ndim
        s[ch_axis] = -1
        return s

    if use_global_stats:
        def fn(a, rm, rv, w=None, b=None):
            inv = jax.lax.rsqrt(rv.astype(jnp.float32) + epsilon)
            out = (a.astype(jnp.float32) - rm.reshape(bshape(a.ndim))) * \
                inv.reshape(bshape(a.ndim))
            if w is not None:
                out = out * w.reshape(bshape(a.ndim))
            if b is not None:
                out = out + b.reshape(bshape(a.ndim))
            return out.astype(a.dtype)
        args = [x, running_mean, running_var]
        if weight is not None:
            args.append(weight)
        if bias is not None:
            args.append(bias)
        return apply(fn, *args, name="batch_norm")

    # training: compute batch stats (optionally psum across dp axis)
    def fn(a, w=None, b=None):
        af = a.astype(jnp.float32)
        if sync_axis is not None:
            cnt = jax.lax.psum(jnp.asarray(np.prod([a.shape[i] for i in red_axes]),
                                           jnp.float32), sync_axis)
            s = jax.lax.psum(jnp.sum(af, axis=red_axes), sync_axis)
            ss = jax.lax.psum(jnp.sum(af * af, axis=red_axes), sync_axis)
            mean = s / cnt
            var = ss / cnt - mean * mean
        else:
            mean = jnp.mean(af, axis=red_axes)
            var = jnp.var(af, axis=red_axes)
        inv = jax.lax.rsqrt(var + epsilon)
        out = (af - mean.reshape(bshape(a.ndim))) * inv.reshape(bshape(a.ndim))
        if w is not None:
            out = out * w.reshape(bshape(a.ndim))
        if b is not None:
            out = out + b.reshape(bshape(a.ndim))
        return out.astype(a.dtype), mean, var

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    out, mean_t, var_t = apply(fn, *args, name="batch_norm", multi=True)

    if running_mean is not None and isinstance(running_mean, Tensor):
        m = float(momentum) if not isinstance(momentum, Tensor) else momentum._value
        rm_new = running_mean._value * m + mean_t._value.astype(running_mean.dtype) * (1 - m)
        rv_new = running_var._value * m + var_t._value.astype(running_var.dtype) * (1 - m)
        running_mean._replace(rm_new)
        running_var._replace(rv_new)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) \
        else [normalized_shape]
    naxes = tuple(range(-len(ns), 0))

    def fn(a, w=None, b=None):
        af = a.astype(jnp.float32)
        mean = jnp.mean(af, axis=naxes, keepdims=True)
        var = jnp.var(af, axis=naxes, keepdims=True)
        out = (af - mean) * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            out = out * w.astype(jnp.float32)
        if b is not None:
            out = out + b.astype(jnp.float32)
        return out.astype(a.dtype)
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (Llama-family). fp32 accumulation, bf16 in/out on TPU."""
    def fn(a, w=None):
        af = a.astype(jnp.float32)
        ms = jnp.mean(af * af, axis=-1, keepdims=True)
        out = af * jax.lax.rsqrt(ms + epsilon)
        if w is not None:
            out = out * w.astype(jnp.float32)
        return out.astype(a.dtype)
    if weight is not None:
        return apply(fn, x, weight, name="rms_norm")
    return apply(fn, x, name="rms_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW",
                  name=None):
    channel_last = data_format[-1] == "C" and len(data_format) > 2
    ch_axis = (x.ndim - 1) if channel_last else 1
    red_axes = tuple(i for i in range(2, x.ndim)) if not channel_last else \
        tuple(i for i in range(1, x.ndim - 1))

    def fn(a, w=None, b=None):
        af = a.astype(jnp.float32)
        mean = jnp.mean(af, axis=red_axes, keepdims=True)
        var = jnp.var(af, axis=red_axes, keepdims=True)
        out = (af - mean) * jax.lax.rsqrt(var + eps)
        if w is not None:
            s = [1] * a.ndim
            s[ch_axis] = -1
            out = out * w.reshape(s)
        if b is not None:
            s = [1] * a.ndim
            s[ch_axis] = -1
            out = out + b.reshape(s)
        return out.astype(a.dtype)
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = data_format[-1] == "C" and len(data_format) > 2

    def fn(a, w=None, b=None):
        if channel_last:
            perm_in = list(range(a.ndim))
            a_nchw = jnp.moveaxis(a, -1, 1)
        else:
            a_nchw = a
        n, c = a_nchw.shape[0], a_nchw.shape[1]
        g = int(num_groups)
        af = a_nchw.astype(jnp.float32).reshape(n, g, c // g, -1)
        mean = jnp.mean(af, axis=(2, 3), keepdims=True)
        var = jnp.var(af, axis=(2, 3), keepdims=True)
        out = ((af - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a_nchw.shape)
        s = [1, c] + [1] * (a_nchw.ndim - 2)
        if w is not None:
            out = out * w.reshape(s)
        if b is not None:
            out = out + b.reshape(s)
        out = out.astype(a.dtype)
        return jnp.moveaxis(out, 1, -1) if channel_last else out
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                        name=None):
    channel_last = data_format[-1] == "C" and len(data_format) > 2

    def fn(a):
        ch_axis = a.ndim - 1 if channel_last else 1
        sq = jnp.square(a.astype(jnp.float32))
        moved = jnp.moveaxis(sq, ch_axis, -1)
        half = size // 2
        padded = jnp.pad(moved, [(0, 0)] * (moved.ndim - 1) + [(half, size - 1 - half)])
        windows = jnp.stack([padded[..., i:i + moved.shape[-1]] for i in range(size)],
                            axis=0)
        summed = jnp.sum(windows, axis=0)
        denom = jnp.power(k + alpha * summed, beta)
        out = a.astype(jnp.float32) / jnp.moveaxis(denom, -1, ch_axis)
        return out.astype(a.dtype)
    return apply(fn, x, name="local_response_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        if p == 2:
            n = jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=True))
        else:
            n = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=True),
                          1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return apply(fn, x, name="normalize")
