"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py).

All pooling lowers to lax.reduce_window — XLA's native windowed
reduction, fused and MXU-adjacent on TPU.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..._core.tensor import Tensor, apply, unwrap

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d", "lp_pool1d", "lp_pool2d", "max_unpool1d",
    "max_unpool2d", "max_unpool3d", "fractional_max_pool2d",
    "fractional_max_pool3d",
]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(unwrap(x)) for x in v) if len(v) == n else \
            tuple(int(unwrap(x)) for x in v) * n
    return (int(unwrap(v)),) * n


def _pad_pairs(padding, nsp):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(int(padding),) * 2] * nsp
    padding = [int(unwrap(p)) for p in padding]
    if len(padding) == nsp:
        return [(p, p) for p in padding]
    if len(padding) == 2 * nsp:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nsp)]
    raise ValueError(f"bad padding {padding}")


def _window(nsp, channel_last, k, s):
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
    return dims, strides


def _full_pads(nsp, channel_last, pads):
    if isinstance(pads, str):
        return pads
    if channel_last:
        return [(0, 0)] + list(pads) + [(0, 0)]
    return [(0, 0), (0, 0)] + list(pads)


def _pool(x, kernel, stride, padding, nsp, data_format, kind, ceil_mode=False,
          exclusive=True, name="pool"):
    channel_last = data_format[-1] == "C"
    k = _tuple(kernel, nsp)
    s = _tuple(stride if stride is not None else kernel, nsp)
    pads = _pad_pairs(padding, nsp)
    dims, strides = _window(nsp, channel_last, k, s)

    def fn(a):
        full_pads = _full_pads(nsp, channel_last, pads)
        if isinstance(full_pads, str):
            pad_cfg = full_pads
        else:
            pad_cfg = full_pads
            if ceil_mode:
                # extend upper pads so that ceil-division windows fit
                pad_cfg = list(pad_cfg)
                sp_axes = range(1, 1 + nsp) if channel_last else range(2, 2 + nsp)
                for i, ax in enumerate(sp_axes):
                    size = a.shape[ax] + pad_cfg[ax][0] + pad_cfg[ax][1]
                    rem = (size - k[i]) % s[i]
                    if rem != 0:
                        pad_cfg[ax] = (pad_cfg[ax][0], pad_cfg[ax][1] + s[i] - rem)
        # init values MUST be python scalars, not arrays: lax.reduce_window
        # only specializes to the differentiable max/add monoid primitives
        # when it recognizes the scalar identity; an array init binds the
        # generic variadic primitive, which fails to linearize under
        # jit(grad(...)) (broke MaxPool backward inside the Trainer)
        if kind == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else \
                int(jnp.iinfo(a.dtype).min)
            return lax.reduce_window(a, init, lax.max,
                                     dims, strides, pad_cfg)
        summed = lax.reduce_window(a, 0.0 if jnp.issubdtype(
            a.dtype, jnp.floating) else 0, lax.add, dims, strides, pad_cfg)
        if exclusive and not isinstance(pad_cfg, str):
            # count in f32 regardless of input dtype (scalar init must
            # match the operand dtype for the monoid specialization)
            ones = jnp.ones(a.shape, jnp.float32)
            counts = lax.reduce_window(ones, 0.0, lax.add,
                                       dims, strides, pad_cfg)
            return (summed / counts).astype(a.dtype) if not jnp.issubdtype(
                a.dtype, jnp.floating) else summed / counts
        denom = float(np.prod(k))
        return summed / denom
    return apply(fn, x, name=name)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _pool(x, kernel_size, stride, padding, 1, fmt, "avg", ceil_mode,
                 exclusive, "avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg", ceil_mode,
                 exclusive, "avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg", ceil_mode,
                 exclusive, "avg_pool3d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    out = _pool(x, kernel_size, stride, padding, 1, fmt, "max", ceil_mode,
                name="max_pool1d")
    if return_mask:
        return out, _pool_argmax(x, kernel_size, stride, padding, 1, fmt, ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, data_format, "max", ceil_mode,
                name="max_pool2d")
    if return_mask:
        return out, _pool_argmax(x, kernel_size, stride, padding, 2, data_format,
                                 ceil_mode)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, data_format, "max", ceil_mode,
                name="max_pool3d")
    if return_mask:
        return out, _pool_argmax(x, kernel_size, stride, padding, 3, data_format,
                                 ceil_mode)
    return out


def _pool_argmax(x, kernel, stride, padding, nsp, data_format, ceil_mode):
    """Indices of max within each window (flattened spatial index)."""
    channel_last = data_format[-1] == "C"
    k = _tuple(kernel, nsp)
    s = _tuple(stride if stride is not None else kernel, nsp)
    pads = _pad_pairs(padding, nsp)
    dims, strides = _window(nsp, channel_last, k, s)

    def fn(a):
        sp_shape = a.shape[1:-1] if channel_last else a.shape[2:]
        flat_idx = np.arange(int(np.prod(sp_shape))).reshape(sp_shape)
        if channel_last:
            idx = jnp.asarray(flat_idx)[None, ..., None]
        else:
            idx = jnp.asarray(flat_idx)[None, None]
        idx = jnp.broadcast_to(idx, a.shape).astype(jnp.int32)
        full_pads = _full_pads(nsp, channel_last, pads)

        def reducer(xv, yv):
            xa, xi = xv
            ya, yi = yv
            take_y = ya > xa
            return jnp.where(take_y, ya, xa), jnp.where(take_y, yi, xi)

        init_a = jnp.asarray(-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                             else jnp.iinfo(a.dtype).min, a.dtype)
        _, out_idx = lax.reduce_window((a, idx), (init_a, jnp.asarray(0, jnp.int32)),
                                       reducer, dims, strides, full_pads)
        return out_idx.astype(jnp.int64)
    return apply(fn, x, name="max_pool_mask")


def _adaptive_axes(in_size, out_size):
    starts = (np.arange(out_size) * in_size) // out_size
    ends = -(-((np.arange(out_size) + 1) * in_size) // out_size)
    return starts, ends


def _adaptive_pool(x, output_size, nsp, data_format, kind, return_mask=False,
                   name="adaptive_pool"):
    channel_last = data_format[-1] == "C"
    out_sp = _tuple(output_size, nsp) if not isinstance(output_size, int) \
        else (int(output_size),) * nsp
    out_sp = tuple(o if o is not None else -1 for o in out_sp)

    def fn(a):
        sp_axes = list(range(1, 1 + nsp)) if channel_last else list(range(2, 2 + nsp))
        in_sp = [a.shape[ax] for ax in sp_axes]
        tgt = [o if o != -1 else i for o, i in zip(out_sp, in_sp)]
        out = a
        for ax, (i_sz, o_sz) in zip(sp_axes, zip(in_sp, tgt)):
            if i_sz == o_sz:
                continue
            if i_sz % o_sz == 0:
                f = i_sz // o_sz
                moved = jnp.moveaxis(out, ax, -1)
                moved = moved.reshape(moved.shape[:-1] + (o_sz, f))
                red = jnp.max(moved, -1) if kind == "max" else jnp.mean(moved, -1)
                out = jnp.moveaxis(red, -1, ax)
            else:
                starts, ends = _adaptive_axes(i_sz, o_sz)
                slices = []
                for st, en in zip(starts, ends):
                    piece = lax.slice_in_dim(out, int(st), int(en), axis=ax)
                    red = jnp.max(piece, axis=ax, keepdims=True) if kind == "max" \
                        else jnp.mean(piece, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out
    result = apply(fn, x, name=name)
    if return_mask:
        mask = _adaptive_argmax(x, out_sp, nsp, channel_last)
        return result, mask
    return result


def _adaptive_argmax(x, out_sp, nsp, channel_last):
    def fn(a):
        sp_axes = list(range(1, 1 + nsp)) if channel_last else list(range(2, 2 + nsp))
        in_sp = [a.shape[ax] for ax in sp_axes]
        sp_shape = tuple(in_sp)
        flat_idx = np.arange(int(np.prod(sp_shape))).reshape(sp_shape)
        idx = jnp.asarray(flat_idx)
        idx = idx[None, ..., None] if channel_last else idx[None, None]
        idx = jnp.broadcast_to(idx, a.shape)
        out_v = a
        out_i = idx
        for ax, (i_sz, o_sz) in zip(sp_axes, zip(in_sp, out_sp)):
            o_sz = o_sz if o_sz != -1 else i_sz
            starts, ends = _adaptive_axes(i_sz, o_sz)
            vs, is_ = [], []
            for st, en in zip(starts, ends):
                pv = lax.slice_in_dim(out_v, int(st), int(en), axis=ax)
                pi = lax.slice_in_dim(out_i, int(st), int(en), axis=ax)
                am = jnp.argmax(pv, axis=ax, keepdims=True)
                vs.append(jnp.take_along_axis(pv, am, axis=ax))
                is_.append(jnp.take_along_axis(pi, am, axis=ax))
            out_v = jnp.concatenate(vs, axis=ax)
            out_i = jnp.concatenate(is_, axis=ax)
        return out_i.astype(jnp.int64)
    return apply(fn, x, name="adaptive_argmax")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "avg", name="adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg",
                          name="adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg",
                          name="adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "max", return_mask,
                          name="adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "NCHW", "max", return_mask,
                          name="adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "NCDHW", "max", return_mask,
                          name="adaptive_max_pool3d")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCL", name=None):
    from ..functional import pooling as _p
    p = float(norm_type)
    xp = apply(lambda a: jnp.power(jnp.abs(a), p), x, name="lp_pow")
    pooled = avg_pool1d(xp, kernel_size, stride, padding, exclusive=False,
                        ceil_mode=ceil_mode, data_format=data_format)
    k = kernel_size if isinstance(kernel_size, int) else int(np.prod(kernel_size))
    return apply(lambda a: jnp.power(a * k, 1.0 / p), pooled, name="lp_root")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCHW", name=None):
    p = float(norm_type)
    xp = apply(lambda a: jnp.power(jnp.abs(a), p), x, name="lp_pow")
    pooled = avg_pool2d(xp, kernel_size, stride, padding, ceil_mode=ceil_mode,
                        exclusive=False, data_format=data_format)
    ks = _tuple(kernel_size, 2)
    k = int(np.prod(ks))
    return apply(lambda a: jnp.power(a * k, 1.0 / p), pooled, name="lp_root")


def _max_unpool(x, indices, kernel_size, stride, padding, output_size, nsp,
                data_format, name):
    channel_last = data_format[-1] == "C"

    def fn(a, idx):
        k = _tuple(kernel_size, nsp)
        s = _tuple(stride if stride is not None else kernel_size, nsp)
        p = _tuple(padding, nsp)
        sp_in = a.shape[1:-1] if channel_last else a.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(unwrap(o)) for o in output_size)[-nsp:]
        else:
            out_sp = tuple((i - 1) * st - 2 * pp + kk
                           for i, st, pp, kk in zip(sp_in, s, p, k))
        if channel_last:
            n, c = a.shape[0], a.shape[-1]
            flat = a.reshape(n, -1, c)
            fidx = idx.reshape(n, -1, c)
            out = jnp.zeros((n, int(np.prod(out_sp)), c), a.dtype)
            out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v),
                                    in_axes=(-1, -1, -1), out_axes=-1))(out, fidx, flat)
            return out.reshape((n,) + out_sp + (c,))
        n, c = a.shape[0], a.shape[1]
        flat = a.reshape(n, c, -1)
        fidx = idx.reshape(n, c, -1)
        out = jnp.zeros((n, c, int(np.prod(out_sp))), a.dtype)
        out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(out, fidx, flat)
        return out.reshape((n, c) + out_sp)
    return apply(fn, x, indices, name=name)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 1,
                       "NCW", "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 2,
                       data_format, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0, data_format="NCDHW",
                 output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 3,
                       data_format, "max_unpool3d")


# ---------------------------------------------------------------------------
# fractional max pooling (Graham 2015; reference nn/functional/pooling.py
# fractional_max_pool2d/3d + phi FractionalStartIndex/EndIndex math)
# ---------------------------------------------------------------------------
def _fractional_bounds(in_size, out_size, u0, pool_size=0):
    """Per-output-index [start, end) windows — exact phi kernel math
    (paddle/phi/kernels/funcs/pooling.h FractionalRationalU/Start/End)."""
    alpha = in_size / out_size
    if pool_size > 0:
        u = u0
    else:
        base = in_size // out_size
        u_max1 = (base + 2) / alpha - 1
        u_max2 = (in_size + 1 - base) / alpha - (out_size - 1)
        u = u0 * min(u_max1, u_max2)
    off = int(u * alpha)
    starts, ends = [], []
    for i in range(out_size):
        s = int((i + u) * alpha) - off
        e = s + pool_size if pool_size > 0 else \
            int((i + 1 + u) * alpha) - off
        starts.append(max(0, min(s, in_size - 1)))
        ends.append(max(1, min(e, in_size)))
    return starts, ends


def _fractional_pool(x, output_size, kernel_size, random_u, return_mask,
                     nsp, name):
    from ..._core.state import prng

    xv = unwrap(x)
    spatial = xv.shape[-nsp:]
    outs = _tuple(output_size, nsp)
    ks = _tuple(kernel_size, nsp) if kernel_size is not None else (0,) * nsp
    if random_u is None:
        u0 = float(jax.random.uniform(prng.next_key(), ()))
    else:
        u0 = float(random_u)
        if not 0 < u0 < 1:
            raise ValueError(f"random_u must be in (0, 1), got {u0}")

    dim_idx = []   # per spatial dim: gather index (out, maxk) + valid mask
    for d in range(nsp):
        starts, ends = _fractional_bounds(spatial[d], outs[d], u0, ks[d])
        maxk = max(e - s for s, e in zip(starts, ends))
        gi = np.zeros((outs[d], maxk), np.int32)
        gm = np.zeros((outs[d], maxk), bool)
        for i, (s, e) in enumerate(zip(starts, ends)):
            w = e - s
            gi[i, :w] = np.arange(s, e)
            gi[i, w:] = s
            gm[i, :w] = True
        dim_idx.append((gi, gm))

    # host-side table: flat input spatial index for every (output cell,
    # window slot); the argmax over flattened window slots maps through it
    kshape = tuple(g.shape[1] for g, _ in dim_idx)
    grids = np.meshgrid(*[np.arange(o) for o in outs], indexing="ij")
    tbl = np.zeros(tuple(outs) + (int(np.prod(kshape)),), np.int64)
    for slot in range(int(np.prod(kshape))):
        rem, offs = slot, []
        for d in range(nsp):
            stride = int(np.prod(kshape[d + 1:]))
            offs.append(rem // stride)
            rem %= stride
        flat = np.zeros(tuple(outs), np.int64)
        for d in range(nsp):
            flat = flat * spatial[d] + dim_idx[d][0][grids[d], offs[d]]
        tbl[..., slot] = flat
    valid = np.ones(tuple(outs) + (int(np.prod(kshape)),), bool)
    for slot in range(int(np.prod(kshape))):
        rem = slot
        for d in range(nsp):
            stride = int(np.prod(kshape[d + 1:]))
            o = rem // stride
            rem %= stride
            valid[..., slot] &= dim_idx[d][1][grids[d], o]

    def fn(a):
        lead = a.shape[:-nsp]
        nl = len(lead)
        neg = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else \
            jnp.iinfo(a.dtype).min
        out = a
        for d in range(nsp):
            gi, _ = dim_idx[d]
            axis = nl + 2 * d  # earlier dims already expanded to (out, k)
            out = jnp.take(out, jnp.asarray(gi.reshape(-1)), axis=axis)
            out = out.reshape(out.shape[:axis] + gi.shape +
                              out.shape[axis + 1:])
        # windows → lead + outs + (K,) with invalid slots masked
        perm = (tuple(range(nl)) +
                tuple(nl + 2 * d for d in range(nsp)) +
                tuple(nl + 2 * d + 1 for d in range(nsp)))
        wins = out.transpose(perm).reshape(
            lead + tuple(outs) + (int(np.prod(kshape)),))
        wins = jnp.where(jnp.asarray(valid), wins, neg)
        pooled = jnp.max(wins, axis=-1)
        if not return_mask:
            return pooled
        am = jnp.argmax(wins, axis=-1)
        mask = jnp.take_along_axis(
            jnp.broadcast_to(jnp.asarray(tbl), wins.shape), am[..., None],
            axis=-1)[..., 0]
        return pooled, mask

    if return_mask:
        out, mask = apply(fn, x, name=name, multi=True)
        return out, mask
    return apply(fn, x, name=name)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """reference: python/paddle/nn/functional/pooling.py:2087 (phi
    FractionalRationalU/StartIndex/EndIndex window math)."""
    return _fractional_pool(x, output_size, kernel_size, random_u,
                            return_mask, 2, "fractional_max_pool2d")


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_pool(x, output_size, kernel_size, random_u,
                            return_mask, 3, "fractional_max_pool3d")
