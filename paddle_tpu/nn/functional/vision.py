"""Vision functionals (reference: python/paddle/nn/functional/vision.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..._core.tensor import apply

__all__ = ["pixel_shuffle", "pixel_unshuffle", "channel_shuffle", "affine_grid",
           "grid_sample"]


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = int(upscale_factor)

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            oc = c // (r * r)
            out = a.reshape(n, oc, r, r, h, w)
            out = out.transpose(0, 1, 4, 2, 5, 3)
            return out.reshape(n, oc, h * r, w * r)
        n, h, w, c = a.shape
        oc = c // (r * r)
        out = a.reshape(n, h, w, r, r, oc)
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h * r, w * r, oc)
    return apply(fn, x, name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, c, h // r, r, w // r, r)
            out = out.transpose(0, 1, 3, 5, 2, 4)
            return out.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        out = a.reshape(n, h // r, r, w // r, r, c)
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h // r, w // r, c * r * r)
    return apply(fn, x, name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    g = int(groups)

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, g, c // g, h, w)
            return out.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        out = a.reshape(n, h, w, g, c // g)
        return out.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return apply(fn, x, name="channel_shuffle")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    shape = [int(s) for s in (out_shape.tolist() if hasattr(out_shape, "tolist")
                              else out_shape)]

    def fn(th):
        n, _, h, w = shape[0], shape[1], shape[-2], shape[-1]
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
        grid = jnp.einsum("bij,bkj->bki", th.astype(jnp.float32),
                          jnp.broadcast_to(base, (n, h * w, 3)))
        return grid.reshape(n, h, w, 2).astype(th.dtype)
    return apply(fn, theta, name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True,
                name=None):
    def fn(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0].astype(jnp.float32), g[..., 1].astype(jnp.float32)
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(px, py):
            if padding_mode == "border":
                px = jnp.clip(px, 0, w - 1)
                py = jnp.clip(py, 0, h - 1)
                valid = jnp.ones_like(px, bool)
            elif padding_mode == "reflection":
                def reflect(v, size):
                    if align_corners:
                        span = 2 * (size - 1)
                        v = jnp.abs(jnp.mod(v + span, span) * 0 + v)
                        v = jnp.mod(jnp.abs(v), span) if size > 1 else v * 0
                        return jnp.where(v >= size, span - v, v)
                    span = 2 * size
                    v = jnp.mod(jnp.abs(v + 0.5), span)
                    return jnp.where(v >= size, span - v, v) - 0.5
                px = jnp.clip(reflect(px, w), 0, w - 1)
                py = jnp.clip(reflect(py, h), 0, h - 1)
                valid = jnp.ones_like(px, bool)
            else:
                valid = (px >= 0) & (px <= w - 1) & (py >= 0) & (py <= h - 1)
                px = jnp.clip(px, 0, w - 1)
                py = jnp.clip(py, 0, h - 1)
            pxi = px.astype(jnp.int32)
            pyi = py.astype(jnp.int32)
            batch_idx = jnp.arange(n).reshape(n, 1, 1)
            vals = a[batch_idx, :, pyi, pxi]  # (n, gh, gw, c)
            return jnp.where(valid[..., None], vals, 0.0)

        if mode == "nearest":
            out = sample(jnp.round(fx), jnp.round(fy))
            return jnp.moveaxis(out, -1, 1).astype(a.dtype)

        x0, y0 = jnp.floor(fx), jnp.floor(fy)
        x1, y1 = x0 + 1, y0 + 1
        wa = (x1 - fx) * (y1 - fy)
        wb = (fx - x0) * (y1 - fy)
        wc = (x1 - fx) * (fy - y0)
        wd = (fx - x0) * (fy - y0)
        out = (sample(x0, y0) * wa[..., None] + sample(x1, y0) * wb[..., None] +
               sample(x0, y1) * wc[..., None] + sample(x1, y1) * wd[..., None])
        return jnp.moveaxis(out, -1, 1).astype(a.dtype)
    return apply(fn, x, grid, name="grid_sample")
