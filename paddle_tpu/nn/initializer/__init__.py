"""Parameter initializers (reference: python/paddle/nn/initializer/*).

Each initializer generates a concrete jax array from the global PRNG —
initialization is host-side and explicit, so distributed init can shard
deterministically (same seed → same params on every host).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..._core import dtypes as _dt
from ..._core.state import prng

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Dirac", "Orthogonal", "Bilinear", "calculate_gain",
    "set_global_initializer",
]

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels stored NHWC-native: (out, *spatial, in) or paddle (out,in,*sp);
    # we store (spatial..., in, out) for lax.conv — see nn/layer/conv.py
    receptive = int(np.prod(shape[:-2])) if len(shape) > 2 else 1
    fan_in = shape[-2] * receptive
    fan_out = shape[-1] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, param, block=None):
        new = self._generate(tuple(param.shape), param.dtype)
        param._replace(new)
        return param

    def _generate(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self._value = value

    def _generate(self, shape, dtype):
        return jnp.full(shape, self._value, _dt.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self._mean, self._std = mean, std

    def _generate(self, shape, dtype):
        z = jax.random.normal(prng.next_key(), shape, jnp.float32)
        return (self._mean + self._std * z).astype(_dt.convert_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self._mean, self._std, self._a, self._b = mean, std, a, b

    def _generate(self, shape, dtype):
        lo = (self._a - 0.0)
        hi = (self._b - 0.0)
        z = jax.random.truncated_normal(prng.next_key(), lo, hi, shape, jnp.float32)
        return (self._mean + self._std * z).astype(_dt.convert_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self._low, self._high = low, high

    def _generate(self, shape, dtype):
        u = jax.random.uniform(prng.next_key(), shape, jnp.float32,
                               self._low, self._high)
        return u.astype(_dt.convert_dtype(dtype))


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self._fan_in, self._fan_out, self._gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self._gain * math.sqrt(2.0 / (fi + fo))
        z = jax.random.normal(prng.next_key(), shape, jnp.float32) * std
        return z.astype(_dt.convert_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self._fan_in, self._fan_out, self._gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self._gain * math.sqrt(6.0 / (fi + fo))
        u = jax.random.uniform(prng.next_key(), shape, jnp.float32, -limit, limit)
        return u.astype(_dt.convert_dtype(dtype))


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self._fan_in = fan_in
        self._slope = negative_slope
        self._nonlinearity = nonlinearity

    def _generate(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self._nonlinearity, self._slope)
        std = gain / math.sqrt(fi)
        z = jax.random.normal(prng.next_key(), shape, jnp.float32) * std
        return z.astype(_dt.convert_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self._fan_in = fan_in
        self._slope = negative_slope
        self._nonlinearity = nonlinearity

    def _generate(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self._nonlinearity, self._slope)
        limit = gain * math.sqrt(3.0 / fi)
        u = jax.random.uniform(prng.next_key(), shape, jnp.float32, -limit, limit)
        return u.astype(_dt.convert_dtype(dtype))


class Assign(Initializer):
    def __init__(self, value, name=None):
        self._np = np.asarray(value)

    def _generate(self, shape, dtype):
        a = self._np.reshape(shape)
        return jnp.asarray(a).astype(_dt.convert_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self._groups = groups

    def _generate(self, shape, dtype):
        # kernel layout (spatial..., in, out)
        a = np.zeros(shape, dtype=np.float32)
        out_ch, in_ch = shape[-1], shape[-2]
        centers = tuple(s // 2 for s in shape[:-2])
        per = out_ch // self._groups
        for g in range(self._groups):
            for i in range(min(per, in_ch)):
                a[centers + (i, g * per + i)] = 1.0
        return jnp.asarray(a).astype(_dt.convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self._gain = gain

    def _generate(self, shape, dtype):
        rows = shape[-1]
        cols = int(np.prod(shape)) // rows
        flat = jax.random.normal(prng.next_key(), (max(rows, cols), min(rows, cols)),
                                 jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diag(r))
        if rows < cols:
            q = q.T
        return (self._gain * q[:rows, :cols].T.reshape(shape)).astype(
            _dt.convert_dtype(dtype))


# paddle.nn.initializer module-level aliases used by reference code
constant = Constant
normal = Normal
uniform = Uniform


class Bilinear(Initializer):
    """Bilinear-interpolation kernel initializer for transposed-conv
    upsampling (reference: python/paddle/nn/initializer/Bilinear.py).
    Kernel layout here is (spatial..., in, out) — see nn/layer/conv.py."""

    def _generate(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer requires a 4-D weight")
        kh, kw, c_in, c_out = shape
        if kh != kw:
            raise ValueError("Bilinear initializer requires square kernels")
        f = int(np.ceil(kw / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        og = np.ogrid[:kh, :kw]
        filt = ((1 - abs(og[0] / f - c)) *
                (1 - abs(og[1] / f - c))).astype(np.float32)
        w = np.zeros(shape, np.float32)
        for i in range(min(c_in, c_out)):
            w[:, :, i, i] = filt
        if c_in != c_out:  # broadcast pattern for channel-changing upsample
            for o in range(c_out):
                w[:, :, o % c_in, o] = filt
        return jnp.asarray(w).astype(_dt.convert_dtype(dtype))
