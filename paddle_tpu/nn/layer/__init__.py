from . import layers  # noqa: F401
from .layers import Layer  # noqa: F401
