"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import numpy as np

from ..._core import dtypes as _dt
from ..._core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant, Normal, Uniform, XavierUniform
from .layers import Layer, ParamAttr


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, input):
        return input


class Linear(Layer):
    """Weight stored (in_features, out_features) → direct MXU matmul."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self._in_features}, out={self._out_features}"


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter(shape=[1, out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, input):
        return F.dropout(input, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout2d(input, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout3d(input, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):
        return F.alpha_dropout(input, p=self.p, training=self.training)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):
        return F.feature_alpha_dropout(input, p=self.p, training=self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None, max_norm=None, norm_type=2.0,
                 scale_grad_by_freq=False):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx if padding_idx is None or padding_idx >= 0 \
            else num_embeddings + padding_idx
        self._max_norm = max_norm
        self._norm_type = norm_type
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        if self._padding_idx is not None:
            w = self.weight._value
            self.weight._replace(w.at[self._padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx,
                           max_norm=self._max_norm, norm_type=self._norm_type)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, input):
        from ...tensor.manipulation import flatten
        return flatten(input, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, input):
        from ...tensor.manipulation import unflatten
        return unflatten(input, self.axis, self.shape)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format=None, name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "nearest",
                             data_format=self.data_format)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "bilinear",
                             align_corners=True, data_format=self.data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format,
                     pad_from_left_axis=False)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format,
                     pad_from_left_axis=False)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format,
                     pad_from_left_axis=False)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.zeropad2d(x, self.padding, self.data_format)


ZeroPad1D = Pad1D
ZeroPad3D = Pad3D


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, input):
        return F.unfold(input, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, input):
        return F.fold(input, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)
