"""Conv layers (reference: python/paddle/nn/layer/conv.py).

Kernel weights are stored (*spatial, in/groups, out) — HWIO, the layout
XLA:TPU wants — instead of the reference's OIHW. state_dict keys match
the reference; shapes are the TPU-native layout (documented divergence).
"""
from __future__ import annotations

import numpy as np

from .. import functional as F
from ..initializer import KaimingUniform, Uniform
from .layers import Layer


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v) if len(v) == n else tuple(v) * n
    return (int(v),) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nsp, transposed=False,
                 stride=1, padding=0, output_padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW"):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, nsp)
        self._stride = _ntuple(stride, nsp)
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = _ntuple(dilation, nsp)
        self._groups = groups
        self._padding_mode = padding_mode
        self._data_format = data_format
        self._nsp = nsp
        if transposed:
            # (*spatial, out, in/groups)
            shape = self._kernel_size + (out_channels, in_channels // groups)
        else:
            # (*spatial, in/groups, out)
            shape = self._kernel_size + (in_channels // groups, out_channels)
        fan_in = in_channels // groups * int(np.prod(self._kernel_size))
        self.weight = self.create_parameter(
            shape=shape, attr=weight_attr,
            default_initializer=KaimingUniform(fan_in=fan_in,
                                               negative_slope=np.sqrt(5.0),
                                               nonlinearity="leaky_relu"))
        bound = 1.0 / np.sqrt(fan_in)
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound))


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, False, stride,
                         padding, 0, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, False, stride,
                         padding, 0, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, False, stride,
                         padding, 0, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, True, stride,
                         padding, output_padding, dilation, groups, "zeros",
                         weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, output_size, self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, True, stride,
                         padding, output_padding, dilation, groups, "zeros",
                         weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, output_size, self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, True, stride,
                         padding, output_padding, dilation, groups, "zeros",
                         weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, output_size, self._data_format)
