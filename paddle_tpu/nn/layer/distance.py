"""Distance layers (reference: python/paddle/nn/layer/distance.py)."""
from .common import PairwiseDistance, CosineSimilarity  # noqa: F401
