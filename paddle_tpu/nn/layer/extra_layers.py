"""Layers completing nn.__all__ parity (reference: python/paddle/nn/layer/
loss.py HSigmoidLoss/AdaptiveLogSoftmaxWithLoss, layer/rnn.py BiRNN,
layer/container.py ParameterDict, layer/pooling.py FractionalMaxPool2D/3D).
"""
from __future__ import annotations

import numpy as np

from .layers import Layer
from .. import functional as F
from ..initializer import Uniform
from ..._core.tensor import Parameter


class ParameterDict(Layer):
    """reference: nn.ParameterDict — dict-style parameter container."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            self.update(parameters)

    def __getitem__(self, key):
        return self._parameters[key]

    def __setitem__(self, key, param):
        self.add_parameter(key, param)

    def __delitem__(self, key):
        del self._parameters[key]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def __contains__(self, key):
        return key in self._parameters

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()

    def update(self, parameters):
        items = parameters.items() if hasattr(parameters, "items") \
            else parameters
        for k, v in items:
            self.add_parameter(k, v)
        return self


class BiRNN(Layer):
    """reference: nn.BiRNN (layer/rnn.py:1426) — runs a forward and a
    backward cell and concatenates outputs along the last axis."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        from .rnn import RNN
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        st_fw = st_bw = None
        if initial_states is not None:
            st_fw, st_bw = initial_states
        out_fw, fin_fw = self.rnn_fw(inputs, st_fw, sequence_length, **kwargs)
        out_bw, fin_bw = self.rnn_bw(inputs, st_bw, sequence_length, **kwargs)
        from ...tensor.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (fin_fw, fin_bw)


class HSigmoidLoss(Layer):
    """reference: nn.HSigmoidLoss (layer/loss.py:477)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if (num_classes < 2) and (not is_custom):
            raise ValueError("num_classes must not be less than 2 "
                             "with default tree")
        self._feature_size = feature_size
        self._num_classes = num_classes
        self._is_custom = is_custom
        self._is_sparse = is_sparse
        rows = num_classes if is_custom else num_classes - 1
        bound = float(np.sqrt(1.0 / feature_size))
        self.weight = self.create_parameter(
            [rows, feature_size], attr=weight_attr,
            default_initializer=Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [rows, 1], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias, path_table=path_table,
                               path_code=path_code,
                               is_sparse=self._is_sparse)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """reference: nn.AdaptiveLogSoftmaxWithLoss (layer/loss.py:2393) —
    head [in, c0 + n_clusters] plus per-cluster low-rank tail projections
    with dims divided by div_value**(i+1)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if any(cutoffs[i] >= cutoffs[i + 1] for i in range(len(cutoffs) - 1)) \
                or any(c <= 0 for c in cutoffs) or cutoffs[-1] > n_classes:
            raise ValueError("cutoffs should be a sequence of unique, "
                             "positive, increasing integers < n_classes")
        if cutoffs[-1] != n_classes:
            cutoffs = cutoffs + [n_classes]
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs
        self.div_value = div_value
        n_clusters = len(cutoffs) - 1
        head_size = cutoffs[0] + n_clusters
        self.head_weight = self.create_parameter(
            [in_features, head_size], attr=weight_attr)
        self.head_bias = self.create_parameter(
            [head_size], attr=bias_attr, is_bias=True) if head_bias else None
        self.tail_weights = []
        for i in range(n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = cutoffs[i + 1] - cutoffs[i]
            proj = self.create_parameter([in_features, hsz],
                                         attr=weight_attr)
            out = self.create_parameter([hsz, osz], attr=weight_attr)
            self.add_parameter(f"tail_proj_{i}", proj)
            self.add_parameter(f"tail_out_{i}", out)
            self.tail_weights.append([proj, out])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:], head_bias=self.head_bias)

    def log_prob(self, input):
        """Full (N, n_classes) log-probabilities."""
        import jax
        import jax.numpy as jnp
        from ..._core.tensor import apply

        cutoffs = self.cutoffs
        n_clusters = len(cutoffs) - 1
        c0 = cutoffs[0]

        def fn(x, hw, *rest):
            bias = rest[-1] if self.head_bias is not None else None
            tails = rest[:2 * n_clusters]
            head = x @ hw
            if bias is not None:
                head = head + bias
            head_lp = jax.nn.log_softmax(head, axis=-1)
            outs = [head_lp[:, :c0]]
            for i in range(n_clusters):
                proj, w = tails[2 * i], tails[2 * i + 1]
                t_lp = jax.nn.log_softmax((x @ proj) @ w, axis=-1)
                outs.append(t_lp + head_lp[:, c0 + i][:, None])
            return jnp.concatenate(outs, axis=-1)

        args = [input, self.head_weight]
        args += [w for pair in self.tail_weights for w in pair]
        if self.head_bias is not None:
            args.append(self.head_bias)
        return apply(fn, *args, name="adaptive_log_prob")

    def predict(self, input):
        from ...tensor.search import argmax
        return argmax(self.log_prob(input), axis=-1)


class FractionalMaxPool2D(Layer):
    """reference: nn.FractionalMaxPool2D (layer/pooling.py)."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)
