"""nn.Layer base class (reference: python/paddle/nn/layer/layers.py).

Paddle-shaped module system with a functional escape hatch:
`functional_state()` / `functional_call()` turn any Layer tree into a
pure (params, buffers, inputs) → outputs function — the form jit /
value_and_grad / pjit consume. That bridge replaces the reference's
dy2static program translation (python/paddle/jit/dy2static) wholesale.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict

import numpy as np
import jax
import jax.numpy as jnp

from ..._core import dtypes as _dt
from ..._core.state import no_grad_ctx
from ..._core.tensor import Parameter, Tensor, unwrap
from ..initializer import Constant, XavierUniform, Initializer


class ParamAttr:
    """reference: python/paddle/base/param_attr.py."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        return ParamAttr()


_name_counter = {}


def _unique_name(prefix):
    i = _name_counter.get(prefix, 0)
    _name_counter[prefix] = i + 1
    return f"{prefix}_{i}"


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = _dt.convert_dtype(dtype) if dtype else _dt.get_default_dtype()
        self._parameters = OrderedDict()
        self._buffers = OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._sub_layers = OrderedDict()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._full_name = _unique_name(self._name_scope)
        self._casted_by_pure_fp16 = False

    # -- registration -------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() first")
            layers[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name] = Parameter(value._value, name=params[name].name)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)
        elif layers is not None and name in layers:
            if value is None:
                layers.pop(name)
                object.__setattr__(self, name, None)
            else:
                object.__setattr__(self, name, value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        if "_parameters" in self.__dict__ and name in self.__dict__["_parameters"]:
            return self.__dict__["_parameters"][name]
        if "_sub_layers" in self.__dict__ and name in self.__dict__["_sub_layers"]:
            return self.__dict__["_sub_layers"][name]
        if "_buffers" in self.__dict__ and name in self.__dict__["_buffers"]:
            return self.__dict__["_buffers"][name]
        raise AttributeError(
            f"'{self.__class__.__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for d in (self._parameters, self._buffers, self._sub_layers):
            if name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # -- parameter creation -------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        d = _dt.convert_dtype(dtype) if dtype is not None else self._dtype
        if default_initializer is None:
            init = attr.initializer if attr.initializer is not None else \
                (Constant(0.0) if is_bias else XavierUniform())
        else:
            init = attr.initializer if attr.initializer is not None else \
                default_initializer
        value = init._generate(tuple(int(s) for s in shape), d)
        p = Parameter(value, name=attr.name or _unique_name("param"),
                      trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_variable(self, name=None, persistable=None, dtype=None):
        d = _dt.convert_dtype(dtype) if dtype is not None else self._dtype
        t = Tensor(jnp.zeros((), d), name=name)
        t.persistable = bool(persistable)
        return t

    create_tensor = create_variable

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        return tensor

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None or id(layer) in layers_set:
                continue
            layers_set.add(id(layer))
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(prefix=sub_prefix,
                                             include_self=False,
                                             layers_set=layers_set)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def full_name(self):
        return self._full_name

    # -- mode ---------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            skip = False
            for lname, layer in self.named_sublayers(include_self=True):
                bn = name.split(".")[-1]
                if name == (f"{lname}.{bn}" if lname else bn) and \
                        bn in layer._non_persistable_buffer_names_set:
                    skip = True
                    break
            if not skip:
                dest[structured_name_prefix + name] = b
        return dest

    def to_static_state_dict(self, destination=None, include_sublayers=True,
                             structured_name_prefix="", use_hook=True,
                             keep_vars=True):
        """Reference parity (nn/layer/layers.py:2044): the static-graph
        flavor of state_dict. There is no separate static VarBase here —
        keep_vars=False detaches the entries from the tape, matching the
        reference's variable conversion."""
        d = self.state_dict(destination=destination,
                            include_sublayers=include_sublayers,
                            structured_name_prefix=structured_name_prefix,
                            use_hook=use_hook)
        if not keep_vars:
            # detach IN PLACE: a caller-supplied destination must hold
            # the same (detached) entries as the returned dict
            for k, v in d.items():
                if isinstance(v, Tensor):
                    d[k] = v.detach()
        return d

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        matched = {}
        for k, v in state_dict.items():
            if k in own:
                matched[k] = v
            else:
                unexpected.append(k)
        for k in own:
            if k not in matched:
                missing.append(k)
        for k, v in matched.items():
            target = own[k]
            raw = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            if tuple(raw.shape) != tuple(target._value.shape):
                raise ValueError(
                    f"shape mismatch for {k}: {raw.shape} vs {target._value.shape}")
            target._replace(raw.astype(target.dtype))
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- dtype / device -----------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._to_dtype(_dt.convert_dtype(dtype))
        return self

    def _to_dtype(self, d):
        for _, p in self.named_parameters():
            if _dt.is_floating_point_dtype(p.dtype):
                p._replace(p._value.astype(d))
        for _, b in self.named_buffers():
            if b is not None and _dt.is_floating_point_dtype(b.dtype):
                b._replace(b._value.astype(d))
        for _, l in self.named_sublayers(include_self=True):
            l._dtype = d
        return self

    def astype(self, dtype):
        return self._to_dtype(_dt.convert_dtype(dtype))

    def float(self):
        return self._to_dtype(_dt.float32)

    def bfloat16(self):
        return self._to_dtype(_dt.bfloat16)

    def half(self):
        return self._to_dtype(_dt.float16)

    def clear_gradients(self, set_to_zero=True):
        for p in self.parameters():
            p.grad = None

    # -- functional bridge (tpu-native) -------------------------------------
    def functional_state(self):
        """→ (params: {name: raw array}, buffers: {name: raw array})."""
        params = {n: p._value for n, p in self.named_parameters()}
        buffers = {n: b._value for n, b in self.named_buffers() if b is not None}
        return params, buffers

    @contextlib.contextmanager
    def _swapped_state(self, params=None, buffers=None):
        saved = []
        try:
            if params:
                own = dict(self.named_parameters())
                for n, raw in params.items():
                    p = own[n]
                    saved.append((p, p._value))
                    p._value = raw
            if buffers:
                ownb = dict(self.named_buffers())
                for n, raw in buffers.items():
                    if n in ownb and ownb[n] is not None:
                        b = ownb[n]
                        saved.append((b, b._value))
                        b._value = raw
            yield
        finally:
            for t, old in saved:
                t._value = old

    def functional_call(self, params, buffers, *args, return_buffers=False,
                        **kwargs):
        """Pure call: run forward with the given raw param/buffer arrays.

        Tensors produced inside are unwrapped to raw arrays on return so
        the result is a clean pytree for jit/grad.
        """
        with self._swapped_state(params, buffers):
            out = self(*args, **kwargs)
            result = jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))
            if return_buffers:
                new_buffers = {n: b._value for n, b in self.named_buffers()
                               if b is not None}
                return result, new_buffers
        return result
