"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..._core import dtypes as _dt
from ..._core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None,
                 name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        # reference contract (functional/norm.py trainable_statistics):
        # None = batch stats in train, moving stats in eval; explicit
        # False = mini-batch statistics ALWAYS, eval included. Pass it
        # through untouched — F.batch_norm implements exactly that
        # split, and collapsing False into None silently changed eval
        # numerics for users who asked for trainable statistics.
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features],
                                                       _dt.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features],
                                                          _dt.float32)))

    def forward(self, input):
        return F.batch_norm(input, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (act arg)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=None, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, input):
        out = super().forward(input)
        if self._act == "relu":
            return F.relu(out)
        if self._act:
            return getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCL" if data_format in ("NCL", "NC") else "NLC",
                         use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None,
                 name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN: stats psum over the data-parallel mesh axis when
    run under shard_map; identical to BatchNorm outside pjit (reference:
    python/paddle/nn/layer/norm.py SyncBatchNorm + NCCL allreduce)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None, sync_axis="dp"):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format)
        self._sync_axis = sync_axis

    def forward(self, x):
        sync = None
        from ...distributed import env as _denv
        if _denv.inside_shard_map() and self.training:
            sync = self._sync_axis
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats,
                            sync_axis=sync)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            if layer.weight is not None:
                new.weight.set_value(layer.weight)
            if layer.bias is not None:
                new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._normalized_shape = [normalized_shape] if isinstance(
            normalized_shape, int) else list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter(
            shape=self._normalized_shape, attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class RMSNorm(Layer):
    """TPU-first RMSNorm (Llama family; reference: incubate fused_rms_norm)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_channels], attr=weight_attr,
            default_initializer=Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(shape=[num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, input):
        return F.instance_norm(input, weight=self.weight, bias=self.bias,
                               eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, input):
        return F.local_response_norm(input, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..initializer import Normal
        self.weight_u = self.create_parameter([h], default_initializer=Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter([w], default_initializer=Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, x):
        from ...tensor import manipulation as M
        w = x
        if self._dim != 0:
            w = M.moveaxis(w, self._dim, 0)
        h = w.shape[0]
        wm = M.reshape(w, [h, -1])
        u, v = self.weight_u._value, self.weight_v._value
        wr = wm._value
        for _ in range(self._power_iters):
            v = wr.T @ u
            v = v / (jnp.linalg.norm(v) + self._epsilon)
            u = wr @ v
            u = u / (jnp.linalg.norm(u) + self._epsilon)
        self.weight_u._replace(u)
        self.weight_v._replace(v)
        sigma = u @ wr @ v
        out = M.reshape(Tensor(wr / sigma), list(w.shape))
        if self._dim != 0:
            out = M.moveaxis(out, 0, self._dim)
        return out
