"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class _Pool(Layer):
    def __init__(self, kernel_size=None, stride=None, padding=0, ceil_mode=False,
                 **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        for k, v in kw.items():
            setattr(self, k, v)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, exclusive=exclusive)

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.exclusive, self.ceil_mode)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, exclusive=exclusive,
                         data_format=data_format)

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            data_format=self.data_format)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, exclusive=exclusive,
                         data_format=data_format)

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            data_format=self.data_format)


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode,
                         return_mask=return_mask)

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode,
                         return_mask=return_mask, data_format=data_format)

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode, self.data_format)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode,
                         return_mask=return_mask, data_format=data_format)

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode, self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool1d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool2d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCDHW",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)
