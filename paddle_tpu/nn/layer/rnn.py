"""RNN layers (reference: python/paddle/nn/layer/rnn.py).

TPU-native driver: the time loop is lax.scan (compiled once, no Python
loop under jit), replacing the reference's per-step dygraph loop /
cuDNN RNN kernels.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor, apply
from .. import functional as F
from ..initializer import Uniform
from .layers import Layer
from .container import LayerList


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0,
                           batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        sh = self.state_shape
        if isinstance(sh, (list, tuple)) and isinstance(sh[0], (list, tuple)):
            return tuple(Tensor(jnp.full((batch,) + tuple(s), init_value,
                                         batch_ref.dtype)) for s in sh)
        return Tensor(jnp.full((batch,) + tuple(sh), init_value, batch_ref.dtype))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def fn(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, name="rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def fn(x, hp, cp, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hp @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            cn = f * cp + i * g
            hn = o * jnp.tanh(cn)
            return hn, cn
        hn, cn = apply(fn, inputs, h, c, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh, name="lstm_cell", multi=True)
        return hn, (hn, cn)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, hp, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = hp @ wh.T + bh
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
            return (1.0 - z) * n + z * hp
        hn = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                   self.bias_ih, self.bias_hh, name="gru_cell")
        return hn, hn


class RNN(Layer):
    """Generic cell driver (reference RNN wrapper) using lax.scan."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        # scan over time using cell's pure function
        time_axis = 0 if self.time_major else 1
        T = inputs.shape[time_axis]
        outs = []
        states = initial_states
        idx = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in idx:
            from ...tensor import manipulation as M
            xt = M.squeeze(M.slice(inputs, [time_axis], [t], [t + 1]), [time_axis])
            y, states = self.cell(xt, states)
            outs.append(y)
        if self.is_reverse:
            outs = outs[::-1]
        from ...tensor.manipulation import stack
        return stack(outs, axis=time_axis), states


class _MultiLayerRNNBase(Layer):
    """Fused multi-layer (bi)directional driver: one lax.scan per layer
    direction over raw arrays — the compiled path used by jit."""

    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, activation=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        self.num_directions = ndir
        cell_cls = {"RNN_TANH": SimpleRNNCell, "RNN_RELU": SimpleRNNCell,
                    "LSTM": LSTMCell, "GRU": GRUCell}[self.MODE]
        self.cells = LayerList()
        for layer in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if layer == 0 else hidden_size * ndir
                kw = {}
                if self.MODE in ("RNN_TANH", "RNN_RELU"):
                    kw["activation"] = "tanh" if self.MODE == "RNN_TANH" else "relu"
                self.cells.append(cell_cls(in_sz, hidden_size,
                                           weight_ih_attr=weight_ih_attr,
                                           weight_hh_attr=weight_hh_attr,
                                           bias_ih_attr=bias_ih_attr,
                                           bias_hh_attr=bias_hh_attr, **kw))

    def _cell_step(self, cell, x, state):
        return cell(x, state)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        time_axis = 0 if self.time_major else 1
        from ...tensor import manipulation as M
        x = inputs
        B = x.shape[1 if self.time_major else 0]
        ndir = self.num_directions
        final_h, final_c = [], []
        is_lstm = self.MODE == "LSTM"

        if initial_states is not None:
            if is_lstm:
                h0_all, c0_all = initial_states
            else:
                h0_all = initial_states
                c0_all = None

        for layer in range(self.num_layers):
            outs_dir = []
            for d in range(ndir):
                cell = self.cells[layer * ndir + d]
                if initial_states is not None:
                    hi = h0_all[layer * ndir + d]
                    state = (hi, c0_all[layer * ndir + d]) if is_lstm else hi
                else:
                    state = None
                T = x.shape[time_axis]
                outs = []
                idx = range(T - 1, -1, -1) if d == 1 else range(T)
                for t in idx:
                    xt = M.squeeze(M.slice(x, [time_axis], [t], [t + 1]), [time_axis])
                    y, state = cell(xt, state)
                    outs.append(y)
                if d == 1:
                    outs = outs[::-1]
                outs_dir.append(M.stack(outs, axis=time_axis))
                if is_lstm:
                    final_h.append(state[0])
                    final_c.append(state[1])
                else:
                    final_h.append(state)
            x = outs_dir[0] if ndir == 1 else M.concat(outs_dir, axis=-1)
            if self.dropout > 0 and layer < self.num_layers - 1:
                x = F.dropout(x, self.dropout, training=self.training)
        h_stack = M.stack(final_h, axis=0)
        if is_lstm:
            c_stack = M.stack(final_c, axis=0)
            return x, (h_stack, c_stack)
        return x, h_stack


class SimpleRNN(_MultiLayerRNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        self.MODE = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction, time_major,
                         dropout, **kwargs)


class LSTM(_MultiLayerRNNBase):
    MODE = "LSTM"


class GRU(_MultiLayerRNNBase):
    MODE = "GRU"
