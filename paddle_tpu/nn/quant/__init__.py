"""paddle.nn.quant (reference: python/paddle/nn/quant/__init__.py):
weight-only quantized linear ops for LLM inference. Maps onto the
quantization module's int8/int4 PTQ kernels (dequant fused into the
matmul by XLA — the MXU path)."""
from __future__ import annotations

from ..layer.layers import Layer
from ...quantization import (  # noqa: F401
    weight_quantize, weight_dequantize, weight_only_linear,
)

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
           "weight_quantize", "weight_dequantize"]


class Stub(Layer):
    """reference: nn/quant/Stub — placeholder layer the quantization
    passes replace with observers/quanters; identity until configured."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """reference: llm.int8 linear (outlier-split CUDA kernel). TPU path:
    the weight-only int8 matmul already runs mixed precision with fp32
    accumulation on the MXU, which covers the outlier range the CUDA
    kernel splits out — same math, one fused kernel."""
    return weight_only_linear(x, weight, bias=bias,
                              weight_scale=weight_scale,
                              weight_dtype="int8")
