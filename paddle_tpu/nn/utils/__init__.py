"""nn.utils (reference: python/paddle/nn/utils/*)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..._core.tensor import Tensor, Parameter


def parameters_to_vector(parameters, name=None):
    vec = jnp.concatenate([p._value.reshape(-1) for p in parameters])
    return Tensor(vec)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        p._replace(v[offset:offset + n].reshape(p._value.shape).astype(p.dtype))
        offset += n
    return parameters


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v / ||v|| (reference:
    python/paddle/nn/utils/weight_norm_hook.py)."""
    w = getattr(layer, name)
    raw = w._value
    if dim is None:
        norm = jnp.sqrt(jnp.sum(jnp.square(raw)))
        g0 = norm.reshape(())
    else:
        axes = tuple(i for i in range(raw.ndim) if i != dim % raw.ndim)
        g0 = jnp.sqrt(jnp.sum(jnp.square(raw), axis=axes))
    v = Parameter(raw, name=(w.name or name) + "_v")
    g = Parameter(g0, name=(w.name or name) + "_g")
    del layer._parameters[name]
    layer.add_parameter(name + "_v", v)
    layer.add_parameter(name + "_g", g)

    def _compute(layer_, _inputs):
        vr = getattr(layer_, name + "_v")._value
        gr = getattr(layer_, name + "_g")._value
        if dim is None:
            w_new = vr * (gr / jnp.sqrt(jnp.sum(jnp.square(vr))))
        else:
            axes = tuple(i for i in range(vr.ndim) if i != dim % vr.ndim)
            norm = jnp.sqrt(jnp.sum(jnp.square(vr), axis=axes, keepdims=True))
            shape = [1] * vr.ndim
            shape[dim % vr.ndim] = -1
            w_new = vr / norm * gr.reshape(shape)
        # place the computed weight as a plain tensor attribute
        object.__setattr__(layer_, name, Tensor(w_new, stop_gradient=False))

    layer.register_forward_pre_hook(_compute)
    _compute(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    v = layer._parameters.pop(name + "_v", None)
    g = layer._parameters.pop(name + "_g", None)
    if v is None:
        return layer
    if g._value.ndim == 0:
        w = v._value * (g._value / jnp.sqrt(jnp.sum(jnp.square(v._value))))
    else:
        w = getattr(layer, name)._value if hasattr(layer, name) else v._value
    object.__setattr__(layer, name, None)
    layer.add_parameter(name, Parameter(w))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    from ..layer.norm import SpectralNorm as _SN
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = _SN(tuple(w.shape), dim=dim, power_iters=n_power_iterations, epsilon=eps)
    raw_param = layer._parameters.pop(name)
    layer.add_sublayer(name + "_sn_helper", sn)
    layer.add_parameter(name + "_orig", raw_param)

    def _compute(layer_, _inputs):
        orig = getattr(layer_, name + "_orig")
        out = layer_._sub_layers[name + "_sn_helper"](orig)
        object.__setattr__(layer_, name, out)

    layer.register_forward_pre_hook(_compute)
    _compute(layer, None)
    return layer


# reference nn/utils/__init__.py re-exports the clip helpers
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: E402,F401
