"""paddle_tpu.observability — runtime observability layer.

The static half of "why was this step slow" is tpulint
(`paddle_tpu.analysis`); this package is the runtime half
(docs/observability.md has the architecture):

  * `compile_telemetry` — a registry every jit entry point reports to:
    compiles / retraces per function with arg-shape signatures, compile
    seconds, a retrace-storm warning (runtime TPL002), and
    `pt_compile_*` Prometheus exposition.
  * `trace_context`    — contextvar-propagated trace ids + parent/child
    spans, so every event recorded while serving a request carries that
    request's identity.
  * `logging`          — structured JSON log lines with per-event-type
    rate limiting; every event also lands in the flight recorder.
  * `flight_recorder`  — a bounded ring of recent structured events
    (spans, compiles, scheduler decisions, errors) dumped to JSON on
    SIGTERM / fault / `/debug/flightrecorder`.
  * `chrome_trace`     — chrome://tracing export of recorded spans,
    one named row per trace id, flow events stitching each request.
  * `device_telemetry` — XLA cost/memory analysis captured per compiled
    entry point (FLOPs, bytes, HBM sizes), per-step MFU + roofline
    gauges against a per-generation peak table, and a device-memory
    accountant (`pt_mfu`, `pt_device_*` on `/metrics`).
  * `pulse`            — telemetry pulse plane: bounded ring-buffer
    time-series derived generically from metrics snapshots (counter
    rates, gauge samples, windowed histogram percentiles), the
    `/debug/pulse` payload + `tools/ptop.py` dashboard feed, and
    anomaly-triggered capture bundles (`PT_CAPTURE_DIR`) rendered by
    `tools/ptdump.py bundle`.
  * `fleet_obs`        — fleet observability primitives: NTP-style
    clock-skew estimation per worker, cross-host span stitching into
    one skew-corrected chrome trace, merged flight-ring dumps, and
    fleet-wide capture bundles (rank 0 pulls every worker's evidence
    into one dir on a pulse trigger).
  * `health`           — jit-safe training-health monitoring: fused
    loss/grad finite checks + grad-norm/update-ratio computed inside
    traced step functions (one batched transfer per step), GradScaler
    found-inf counters, and a NaN-blame pass naming the first
    non-finite-producing layer (`pt_train_*`).

Import cost: stdlib only at import time (jax is imported lazily inside
signature hashing), so `import paddle_tpu.observability` is safe from
anywhere — including the serving stack's innermost loops.
"""
from __future__ import annotations

from . import (  # noqa: F401
    chrome_trace, compile_telemetry, device_telemetry, fleet_obs,
    flight_recorder, health, pulse, trace_context,
)
from . import logging as logging  # noqa: F401,PLC0414 — stdlib-shadowing by design
from .chrome_trace import chrome_trace_doc  # noqa: F401
from .compile_telemetry import (  # noqa: F401
    CompileRegistry, signature_of, track_jit, tracked,
)
from .device_telemetry import (  # noqa: F401
    ACCOUNTANT, COSTS, CostRegistry, MemoryAccountant, device_peaks,
)
from .flight_recorder import FlightRecorder, RECORDER  # noqa: F401
from .health import (  # noqa: F401
    HEALTH, TrainingHealthMonitor, health_stats, nan_blame,
)
from .logging import StructuredLogger, get_logger  # noqa: F401
from .pulse import PulsePlane, PulseRing, PulseSampler  # noqa: F401
from .trace_context import (  # noqa: F401
    Span, bind, current_trace_id, new_trace_id, span,
)

__all__ = [
    "chrome_trace", "compile_telemetry", "device_telemetry",
    "fleet_obs", "flight_recorder", "health", "pulse", "trace_context",
    "logging",
    "PulsePlane", "PulseRing", "PulseSampler",
    "CompileRegistry", "tracked", "track_jit", "signature_of",
    "CostRegistry", "COSTS", "MemoryAccountant", "ACCOUNTANT",
    "device_peaks",
    "TrainingHealthMonitor", "HEALTH", "health_stats", "nan_blame",
    "FlightRecorder", "RECORDER",
    "StructuredLogger", "get_logger",
    "Span", "bind", "span", "new_trace_id", "current_trace_id",
    "chrome_trace_doc",
]
