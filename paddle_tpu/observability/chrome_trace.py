"""chrome://tracing export of span events (aux: observability).

Builds a Trace Event Format document from span dicts (the flight
recorder's `kind == "span"` events, or `utils.trace` ring entries via
`Profiler.export`). Each trace id gets its own named row (tid) so a
request's queued → prefill → decode phases line up on one timeline,
and flow events ("s"/"f") stitch the phases of one trace together
visually even when the spans were recorded from different threads.
"""
from __future__ import annotations

import json
import zlib

__all__ = ["chrome_trace_doc", "from_flight_recorder"]

_UNTRACED_TID = 0


def _flow_id(trace_id):
    return zlib.crc32(trace_id.encode()) & 0x7FFFFFFF


def chrome_trace_doc(spans, pid=0):
    """spans: iterables of dicts with name/t_start/dur_s and optional
    trace_id/span_id/parent_id/args. Returns the chrome-tracing
    document (dict) — `json.dump` it."""
    events = []
    tids = {}                       # trace_id -> row
    per_trace = {}                  # trace_id -> [event index]
    for sp in spans:
        trace_id = sp.get("trace_id")
        if trace_id is None:
            tid = _UNTRACED_TID
        else:
            tid = tids.setdefault(trace_id, len(tids) + 1)
        args = dict(sp.get("args") or {})
        for k in ("trace_id", "span_id", "parent_id"):
            if sp.get(k) is not None:
                args[k] = sp[k]
        ev = {"name": sp["name"], "ph": "X", "pid": pid, "tid": tid,
              "ts": sp["t_start"] * 1e6, "dur": sp["dur_s"] * 1e6}
        if args:
            ev["args"] = args
        if trace_id is not None:
            per_trace.setdefault(trace_id, []).append(len(events))
        events.append(ev)
    # rows named after their trace id; row 0 is the untraced pool
    meta = [{"name": "thread_name", "ph": "M", "pid": pid,
             "tid": _UNTRACED_TID, "args": {"name": "untraced"}}]
    for trace_id, tid in tids.items():
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": f"trace {trace_id}"}})
    # flows: chain each trace's spans in start order
    flows = []
    for trace_id, idxs in per_trace.items():
        if len(idxs) < 2:
            continue
        idxs = sorted(idxs, key=lambda i: events[i]["ts"])
        fid = _flow_id(trace_id)
        first = events[idxs[0]]
        flows.append({"name": "trace", "cat": "flow", "ph": "s",
                      "id": fid, "pid": pid, "tid": first["tid"],
                      "ts": first["ts"] + first.get("dur", 0) / 2})
        for i in idxs[1:]:
            e = events[i]
            flows.append({"name": "trace", "cat": "flow", "ph": "f",
                          "bp": "e", "id": fid, "pid": pid,
                          "tid": e["tid"],
                          "ts": e["ts"] + e.get("dur", 0) / 2})
    return {"traceEvents": meta + events + flows,
            "displayTimeUnit": "ms"}


def from_flight_recorder(recorder=None):
    """Chrome-tracing doc of every span currently in the flight
    recorder (the `/debug/trace` payload)."""
    if recorder is None:
        from . import flight_recorder as _fr
        recorder = _fr.RECORDER
    return chrome_trace_doc(recorder.events(kind="span"))


def dump_chrome_trace(path, recorder=None):
    doc = from_flight_recorder(recorder)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
