"""Compile/retrace telemetry (aux subsystem: observability).

Every jit entry point in the stack reports here: how many times each
function compiled, with which arg-shape signature, how long the
compiles took, and — the number that actually explains a slow TPU step
— how many of those compiles were RETRACES of a function that had
already compiled. tpulint's TPL002 finds retrace *hazards* statically;
this registry is its runtime counterpart, catching the storms that
only shapes-at-runtime can produce.

Mechanics: a `tracked()` wrapper keys calls by the pytree of arg
shapes/dtypes (+ static arg values) — the same thing jax's jit cache
keys on — so a first-seen signature IS a compile. The first call with
a new signature is timed wall-clock; for jax.jit that call blocks
through trace+lower+compile (execution stays async), so the elapsed
time is compile time plus one dispatch, which is the honest cost the
caller paid.

When one function crosses `warn_after` compiles, a warning fires ONCE
through the structured log + flight recorder (runtime TPL002) naming
the churning signatures.

Exposition: `render_prometheus()` emits `pt_compile_total`,
`pt_compile_retraces_total`, `pt_compile_seconds_total` (+ per-function
labelled series); the serving server appends it to `/metrics`.
"""
from __future__ import annotations

import functools
import os
import threading
import time

from .._env import env_float, env_int

__all__ = ["CompileRegistry", "REGISTRY", "tracked", "track_jit",
           "signature_of", "set_context", "snapshot",
           "render_prometheus", "reset"]

DEFAULT_WARN_AFTER = env_int("PADDLE_TPU_RETRACE_WARN")

# first-call wall time below which a compile is attributed to the
# persistent XLA compilation cache (PT_COMPILE_CACHE): a real
# trace+lower+compile of a serving program takes 100s of ms even for
# toy models, a disk cache hit is a deserialize
CACHE_HIT_S = env_float("PT_COMPILE_CACHE_HIT_S")


def signature_of(args, kwargs=None):
    """Hashable arg-shape signature: arrays (anything with
    shape+dtype, incl. Tensors via their value) become
    ('shape', 'dtype'); everything else contributes its repr — the
    static-arg half of jit's cache key. Pytrees are flattened with
    jax's registry so custom nodes (Tensor) decompose correctly."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(
        (args, kwargs or {}))

    def leaf_sig(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return f"{tuple(shape)}:{dtype}"
        r = repr(x)
        return r if len(r) <= 80 else r[:77] + "..."
    return (str(treedef),) + tuple(leaf_sig(l) for l in leaves)


class _FnStats:
    __slots__ = ("name", "calls", "compiles", "compile_seconds",
                 "signatures", "last_signature", "warned")

    def __init__(self, name):
        self.name = name
        self.calls = 0
        self.compiles = 0
        self.compile_seconds = 0.0
        self.signatures = {}       # sig -> call count
        self.last_signature = None
        self.warned = False

    def snap(self):
        return {
            "calls": self.calls,
            "compiles": self.compiles,
            "retraces": max(self.compiles - 1, 0),
            "compile_seconds": self.compile_seconds,
            "distinct_signatures": len(self.signatures),
            "last_signature": list(self.last_signature or ()),
        }


class CompileRegistry:
    def __init__(self, warn_after=DEFAULT_WARN_AFTER, warn_hook=None):
        self._lock = threading.Lock()
        self._fns = {}
        self._context = None
        self.warn_after = warn_after
        # warn_hook(name, stats_dict) — default: structured log event +
        # flight-recorder entry (set at call time so tests can swap it)
        self.warn_hook = warn_hook
        # persistent XLA compilation cache (PT_COMPILE_CACHE): set via
        # note_persistent_cache() when the serving engine wires
        # jax_compilation_cache_dir. While set, compiles whose
        # first-call time beats CACHE_HIT_S are tagged cache hits —
        # the restart-runbook signal that a warm restart skipped its
        # recompiles (docs/reliability.md).
        self.persistent_cache_dir = None
        self.cache_hits = 0

    def note_persistent_cache(self, cache_dir):
        """Record that jax's persistent compilation cache is active at
        `cache_dir` — enables cache-hit attribution in note_call."""
        with self._lock:
            self.persistent_cache_dir = str(cache_dir)

    def set_context(self, **tags):
        """One-shot annotation consumed by the NEXT reported call: when
        that call turns out to be a compile, the tags ride its flight
        "compile" record. The bucketed serving entry points tag the
        power-of-two bucket they chose (`bucket=...`) so a retrace
        storm names the bucket that caused it."""
        with self._lock:
            self._context = tags or None

    # -- reporting -----------------------------------------------------
    def note_call(self, name, signature, elapsed_s=None):
        """Record one call; returns True when it was a compile (the
        signature was never seen for this function)."""
        with self._lock:
            st = self._fns.get(name)
            if st is None:
                st = self._fns[name] = _FnStats(name)
            st.calls += 1
            st.last_signature = signature
            context, self._context = self._context, None
            compiled = signature not in st.signatures
            st.signatures[signature] = st.signatures.get(signature, 0) + 1
            if compiled:
                st.compiles += 1
                if elapsed_s is not None:
                    st.compile_seconds += elapsed_s
                cache_hit = (self.persistent_cache_dir is not None
                             and elapsed_s is not None
                             and elapsed_s < CACHE_HIT_S)
                if cache_hit:
                    self.cache_hits += 1
                retrace = st.compiles > 1
                warn = (not st.warned and
                        st.compiles >= self.warn_after)
                if warn:
                    st.warned = True
                snap = st.snap()
        if not compiled:
            return False
        from . import flight_recorder as _fr
        _fr.record("compile", fn=name, retrace=retrace,
                   n_compiles=snap["compiles"],
                   elapsed_s=elapsed_s, cache_hit=cache_hit,
                   signature=list(signature)[:8],
                   **(context or {}))
        if warn:
            self._warn(name, snap)
        return True

    def _warn(self, name, snap):
        hook = self.warn_hook
        if hook is not None:
            hook(name, snap)
            return
        from . import logging as _log
        _log.get_logger("compile").event(
            "compile.retrace_storm", level="warning", fn=name,
            compiles=snap["compiles"],
            distinct_signatures=snap["distinct_signatures"],
            compile_seconds=snap["compile_seconds"],
            hint=("same function recompiled repeatedly — a shape or "
                  "static-arg churns per call; bucket the shape or hoist "
                  "the static (tpulint TPL002, now observed at runtime)"))

    # -- wrapping ------------------------------------------------------
    def tracked(self, name=None):
        """Decorator: report every call of the wrapped (usually jitted)
        callable to this registry under `name`."""
        def deco(fn):
            label = name or getattr(fn, "__name__", repr(fn))

            @functools.wraps(fn)
            def wrapper(*args, **kwargs):
                # signature BEFORE the call: donated buffers are
                # invalid afterwards
                try:
                    sig = signature_of(args, kwargs)
                except Exception:   # never let telemetry break the call
                    sig = ("<unhashable>",)
                t0 = time.perf_counter()
                out = fn(*args, **kwargs)
                compiled = self.note_call(
                    label, sig, elapsed_s=time.perf_counter() - t0)
                # device cost accounting: a compile captures the new
                # executable's XLA cost/memory analysis (shape-only
                # AOT re-resolve — donated buffers are fine), and
                # every call adds its known FLOPs to the MFU window
                from . import device_telemetry as _dt
                if compiled:
                    _dt.COSTS.capture(label, sig, fn, args, kwargs)
                _dt.COSTS.note_executed(label, sig)
                return out
            wrapper.__wrapped__ = fn
            wrapper._pt_compile_name = label
            return wrapper
        return deco

    # -- exposition ----------------------------------------------------
    def totals(self):
        with self._lock:
            return {
                "compiles": sum(s.compiles for s in self._fns.values()),
                "retraces": sum(max(s.compiles - 1, 0)
                                for s in self._fns.values()),
                "compile_seconds": sum(s.compile_seconds
                                       for s in self._fns.values()),
                "functions": len(self._fns),
                "cache_hits": self.cache_hits,
            }

    def snapshot(self):
        with self._lock:
            return {name: st.snap() for name, st in self._fns.items()}

    def render_prometheus(self):
        t = self.totals()
        out = [
            "# HELP pt_compile_total jit compilations observed "
            "(first call per arg-shape signature).",
            "# TYPE pt_compile_total counter",
            f"pt_compile_total {t['compiles']}",
            "# HELP pt_compile_retraces_total compilations beyond each "
            "function's first (retraces).",
            "# TYPE pt_compile_retraces_total counter",
            f"pt_compile_retraces_total {t['retraces']}",
            "# HELP pt_compile_seconds_total wall seconds paid "
            "compiling (first-call elapsed).",
            "# TYPE pt_compile_seconds_total counter",
            f"pt_compile_seconds_total {t['compile_seconds']:.6f}",
            "# HELP pt_compile_cache_hits_total compiles served from "
            "the persistent XLA compilation cache (PT_COMPILE_CACHE).",
            "# TYPE pt_compile_cache_hits_total counter",
            f"pt_compile_cache_hits_total {t['cache_hits']}",
        ]
        with self._lock:
            stats = sorted(self._fns.values(), key=lambda s: s.name)
            rows = [(s.name, s.compiles, max(s.compiles - 1, 0),
                     s.compile_seconds) for s in stats]
        out.append("# TYPE pt_compile_fn_total counter")
        for name, compiles, retraces, secs in rows:
            out.append(f'pt_compile_fn_total{{fn="{name}"}} {compiles}')
        out.append("# TYPE pt_compile_fn_retraces_total counter")
        for name, compiles, retraces, secs in rows:
            out.append(
                f'pt_compile_fn_retraces_total{{fn="{name}"}} {retraces}')
        out.append("# TYPE pt_compile_fn_seconds_total counter")
        for name, compiles, retraces, secs in rows:
            out.append(
                f'pt_compile_fn_seconds_total{{fn="{name}"}} {secs:.6f}')
        return "\n".join(out) + "\n"

    def reset(self):
        with self._lock:
            self._fns.clear()
            self.cache_hits = 0


REGISTRY = CompileRegistry()


def tracked(name=None, registry=None):
    """Module-level decorator bound to the global registry."""
    return (registry or REGISTRY).tracked(name)


# jit entry points read better as: prefill = track_jit("serving.prefill")(prefill)
track_jit = tracked


def set_context(**tags):
    """Tag the global registry's next reported call (see
    CompileRegistry.set_context)."""
    REGISTRY.set_context(**tags)


def snapshot():
    return REGISTRY.snapshot()


def render_prometheus():
    return REGISTRY.render_prometheus()


def reset():
    REGISTRY.reset()
