"""Device telemetry (aux subsystem: observability).

PR 3 made the host observable (who compiled, who waited); this module
makes the HARDWARE observable. Two halves:

  * **CostRegistry** — whenever a tracked jit entry point compiles
    (`compile_telemetry` detects the fresh arg-shape signature), the
    just-built executable is re-resolved through jax's AOT path and its
    `cost_analysis()` / `memory_analysis()` are captured: FLOPs, bytes
    accessed, argument/output/temp/generated-code HBM sizes. Every
    subsequent *call* of that signature adds its known FLOPs/bytes to
    issued counters, so any step loop that knows its wall time can ask
    "what fraction of peak did the chip just do" — `note_step()` turns
    (issued Δ, step seconds) into an MFU gauge against the per-device
    peak table below, plus roofline arithmetic intensity against peak
    HBM bandwidth. The serving pump calls it every engine step
    (`pt_mfu` on `/metrics`); bench and `hapi.Model.fit` read the same
    counters over their own windows.

  * **MemoryAccountant** — polls `device.memory_stats()` on every
    local device (gracefully absent on CPU) and walks
    `jax.live_arrays()` into a by-shape/dtype breakdown, keeping a
    live-bytes high-water mark. Exposed as `pt_device_*` gauges, in
    the bench snapshot (`hbm_peak_bytes`), and as `device.memory`
    flight-recorder records.

The capture runs one extra shape-only `lower()` + HLO cost analysis
per *new* signature (no second XLA backend compile — measured ~8x
cheaper than the compiled-executable route; set
PADDLE_TPU_DEVICE_COST=full for the executable-level
`memory_analysis()` with temp/generated-code HBM) and is never
allowed to break the wrapped call (every capture is best-effort).
Disable with PADDLE_TPU_DEVICE_COST=0.

Import cost: stdlib only (jax is imported inside functions), matching
the rest of `paddle_tpu.observability`.
"""
from __future__ import annotations

import os

from .._env import env_float, env_str
import threading
import time

__all__ = [
    "PEAK_SPECS", "device_generation", "device_peaks",
    "CostRegistry", "COSTS", "MemoryAccountant", "ACCOUNTANT",
    "note_step", "snapshot", "render_prometheus", "reset",
]

# Per-device peak dense-bf16 FLOP/s and HBM bandwidth (bytes/s) by
# generation — the roofline denominators. The cpu row is deliberately
# generous (no laptop hits 1 TFLOP/s dense) so CPU-run MFU gauges stay
# honest fractions in (0, 1] while still being nonzero and testable.
PEAK_SPECS = {
    "v4":  (275e12, 1.2288e12),
    "v5e": (197e12, 8.10e11),
    "v5p": (459e12, 2.765e12),
    "v6e": (918e12, 1.640e12),
    "cpu": (1e12, 1e11),
}

_COST_ENABLED = env_str("PADDLE_TPU_DEVICE_COST") != "0"


def device_generation():
    """Resolve the accelerator generation key for PEAK_SPECS. Off-TPU
    this is always "cpu" regardless of env hints (a CPU run must never
    be scored against a chip's peak); on TPU the bench's
    PALLAS_AXON_TPU_GEN / PADDLE_TPU_GEN override wins, else the
    device_kind string is matched."""
    try:
        import jax
        dev = jax.local_devices()[0]
    except Exception:
        return "cpu"
    if dev.platform != "tpu":
        return "cpu"
    gen = (env_str("PADDLE_TPU_GEN") or
           os.environ.get("PALLAS_AXON_TPU_GEN"))
    if gen in PEAK_SPECS:
        return gen
    kind = getattr(dev, "device_kind", "").lower()
    for key, pats in (("v6e", ("v6 lite", "v6e")),
                      ("v5e", ("v5 lite", "v5e", "v5litepod")),
                      ("v5p", ("v5p",)),
                      ("v4", ("v4",))):
        if any(p in kind for p in pats):
            return key
    return "v5e"   # conservative: lowest-peak current generation


def device_peaks():
    """(peak_flops_per_s, peak_hbm_bytes_per_s) for ONE local device —
    MFU here is the single-chip convention, same as bench.py.
    PADDLE_TPU_PEAK_FLOPS / PADDLE_TPU_PEAK_BW override numerically
    (e.g. a future generation missing from the table)."""
    flops, bw = PEAK_SPECS[device_generation()]
    flops = env_float("PADDLE_TPU_PEAK_FLOPS", flops)
    bw = env_float("PADDLE_TPU_PEAK_BW", bw)
    return flops, bw


def _aval_bytes(tree):
    import math

    import numpy as np

    total = 0
    for leaf in _flat_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            total += math.prod(shape) * np.dtype(dtype).itemsize
    return int(total)


def _flat_leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


def _analysis_of(fn, args, kwargs):
    """Best-effort (cost, memory) analysis of `fn`'s executable for
    `args`/`kwargs`. Arrays are replaced by ShapeDtypeStructs so
    donated-then-deleted buffers (the trainer's params) never need
    their data.

    Default mode stops at `lower()`: `Lowered.cost_analysis()` gives
    the same FLOPs/bytes-accessed numbers WITHOUT a second XLA backend
    compile (measured ~8x cheaper), and argument/output HBM comes from
    the in/out avals. PADDLE_TPU_DEVICE_COST=full additionally runs
    `lower().compile()` for the executable-level `memory_analysis()`
    (temp + generated-code HBM — the numbers only the compiled
    allocation plan knows)."""
    import jax

    def spec(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return jax.ShapeDtypeStruct(tuple(shape), dtype)
        return x
    sargs = jax.tree_util.tree_map(spec, args)
    skwargs = jax.tree_util.tree_map(spec, kwargs or {})
    lowered = fn.lower(*sargs, **skwargs)
    mem = {"argument_bytes": _aval_bytes((sargs, skwargs)),
           "output_bytes": 0, "temp_bytes": 0, "generated_code_bytes": 0}
    if env_str("PADDLE_TPU_DEVICE_COST") == "full":
        compiled = lowered.compile()
        cost = compiled.cost_analysis()
        m = compiled.memory_analysis()
        mem = {"argument_bytes": int(getattr(
                   m, "argument_size_in_bytes", 0) or 0),
               "output_bytes": int(getattr(
                   m, "output_size_in_bytes", 0) or 0),
               "temp_bytes": int(getattr(
                   m, "temp_size_in_bytes", 0) or 0),
               "generated_code_bytes": int(getattr(
                   m, "generated_code_size_in_bytes", 0) or 0)}
    else:
        cost = lowered.cost_analysis()
        try:
            mem["output_bytes"] = _aval_bytes(lowered.out_info)
        except Exception:
            pass
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost or {}, mem


class _FnCost:
    __slots__ = ("name", "flops", "bytes_accessed", "argument_bytes",
                 "output_bytes", "temp_bytes", "code_bytes",
                 "flops_issued", "bytes_issued", "calls", "captures",
                 "capture_failures")

    def __init__(self, name):
        self.name = name
        # latest-signature static analysis (what one call costs)
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.argument_bytes = 0
        self.output_bytes = 0
        self.temp_bytes = 0
        self.code_bytes = 0
        # issued counters (what all calls cost so far)
        self.flops_issued = 0.0
        self.bytes_issued = 0.0
        self.calls = 0
        self.captures = 0
        self.capture_failures = 0

    def snap(self):
        hbm = self.argument_bytes + self.output_bytes + self.temp_bytes
        return {
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "arithmetic_intensity": (self.flops / self.bytes_accessed
                                     if self.bytes_accessed else None),
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": self.code_bytes,
            "hbm_bytes": hbm,
            "flops_issued": self.flops_issued,
            "bytes_issued": self.bytes_issued,
            "calls": self.calls,
            "captures": self.captures,
            "capture_failures": self.capture_failures,
        }


class CostRegistry:
    """Per-entry-point XLA cost/memory analysis + issued-FLOPs window
    accounting. `capture()` is called by compile_telemetry's tracked
    wrapper on every observed compile; `note_executed()` on every call;
    `note_step()` by whoever owns a step clock (the serving pump)."""

    def __init__(self, enabled=None):
        self._lock = threading.Lock()
        self._by_sig = {}          # (name, signature) -> (flops, bytes)
        self._fns = {}             # name -> _FnCost
        self.enabled = _COST_ENABLED if enabled is None else enabled
        # step-window state (note_step deltas) + MFU gauges
        self._win_flops = 0.0
        self._win_bytes = 0.0
        self.last_mfu = 0.0
        self.peak_mfu = 0.0
        self.last_step_flops = 0.0
        self.last_step_bytes = 0.0
        self.last_intensity = 0.0
        self.steps_measured = 0

    # -- capture (compile time) ---------------------------------------
    def capture(self, name, signature, fn, args, kwargs=None):
        """Record the cost/memory analysis of `fn`'s fresh executable.
        Never raises: telemetry must not break the wrapped call."""
        if not self.enabled:
            return None
        key = (name, signature)
        with self._lock:
            st = self._fns.get(name)
            if st is None:
                st = self._fns[name] = _FnCost(name)
            if key in self._by_sig:
                return None        # e.g. two registries sharing a fn
        if not hasattr(fn, "lower"):
            return None
        try:
            cost, mem = _analysis_of(fn, args, kwargs)
            flops = float(cost.get("flops", 0.0) or 0.0)
            byts = float(cost.get("bytes accessed", 0.0) or 0.0)
            entry = dict(mem, flops=flops, bytes_accessed=byts)
        except Exception:          # noqa: BLE001 — best-effort probe
            with self._lock:
                self._by_sig[key] = (0.0, 0.0)
                st.capture_failures += 1
            return None
        with self._lock:
            self._by_sig[key] = (flops, byts)
            st.captures += 1
            st.flops = flops
            st.bytes_accessed = byts
            st.argument_bytes = entry["argument_bytes"]
            st.output_bytes = entry["output_bytes"]
            st.temp_bytes = entry["temp_bytes"]
            st.code_bytes = entry["generated_code_bytes"]
        from . import flight_recorder as _fr
        _fr.record("device.cost", fn=name, **entry)
        return entry

    # -- accounting (every call) --------------------------------------
    def note_executed(self, name, signature):
        """Add one call's known FLOPs/bytes to the issued counters."""
        with self._lock:
            ent = self._by_sig.get((name, signature))
            st = self._fns.get(name)
            if st is not None:
                st.calls += 1
            if ent is None:
                return
            flops, byts = ent
            if st is not None:
                st.flops_issued += flops
                st.bytes_issued += byts
            self._win_flops += flops
            self._win_bytes += byts

    def issued_totals(self):
        """Cumulative issued FLOPs/bytes, total + per function — the
        raw counters bench/hapi compute their own windows from."""
        with self._lock:
            per_fn = {n: {"flops": s.flops_issued,
                          "bytes": s.bytes_issued}
                      for n, s in self._fns.items()}
            return {
                "flops": sum(v["flops"] for v in per_fn.values()),
                "bytes": sum(v["bytes"] for v in per_fn.values()),
                "per_fn": per_fn,
            }

    # -- MFU / roofline (per step) ------------------------------------
    def note_step(self, elapsed_s):
        """Close one step window: everything issued since the previous
        call ran in `elapsed_s` wall seconds (the caller's clock must
        bracket a synced device step — the serving pump's does). Sets
        the pt_mfu / intensity gauges; returns the step's numbers."""
        with self._lock:
            flops, byts = self._win_flops, self._win_bytes
            self._win_flops = 0.0
            self._win_bytes = 0.0
        if elapsed_s <= 0 or flops <= 0:
            return None
        peak_flops, peak_bw = device_peaks()
        mfu = flops / (elapsed_s * peak_flops)
        with self._lock:
            self.last_mfu = mfu
            self.peak_mfu = max(self.peak_mfu, mfu)
            self.last_step_flops = flops
            self.last_step_bytes = byts
            self.last_intensity = flops / byts if byts else 0.0
            self.steps_measured += 1
        return {"mfu": mfu, "flops": flops, "bytes": byts,
                "step_s": elapsed_s,
                "arithmetic_intensity": self.last_intensity}

    def mfu_over(self, flops, elapsed_s):
        """MFU of an arbitrary (flops, seconds) window — bench/hapi."""
        if elapsed_s <= 0:
            return 0.0
        return flops / (elapsed_s * device_peaks()[0])

    # -- exposition ----------------------------------------------------
    def snapshot(self):
        peak_flops, peak_bw = device_peaks()
        with self._lock:
            fns = {n: s.snap() for n, s in self._fns.items()}
            out = {
                "device_generation": device_generation(),
                "peak_flops_per_s": peak_flops,
                "peak_hbm_bytes_per_s": peak_bw,
                "roofline_ridge_flops_per_byte": peak_flops / peak_bw,
                "mfu": self.last_mfu,
                "mfu_peak": self.peak_mfu,
                "step_flops": self.last_step_flops,
                "step_bytes": self.last_step_bytes,
                "step_arithmetic_intensity": self.last_intensity,
                "steps_measured": self.steps_measured,
                "functions": fns,
            }
        return out

    def render_prometheus(self):
        peak_flops, peak_bw = device_peaks()
        with self._lock:
            rows = sorted(self._fns.values(), key=lambda s: s.name)
            fn_rows = [(s.name, s.flops, s.bytes_accessed,
                        s.argument_bytes + s.output_bytes + s.temp_bytes,
                        s.flops_issued) for s in rows]
            mfu, mfu_peak = self.last_mfu, self.peak_mfu
            sflops, sbytes = self.last_step_flops, self.last_step_bytes
            inten = self.last_intensity
        out = [
            "# HELP pt_mfu Model FLOPs utilization of the last measured "
            "step (XLA-counted FLOPs / step seconds / device peak).",
            "# TYPE pt_mfu gauge",
            f"pt_mfu {mfu:.6g}",
            "# TYPE pt_mfu_peak gauge",
            f"pt_mfu_peak {mfu_peak:.6g}",
            "# HELP pt_step_flops XLA-counted FLOPs issued in the last "
            "measured step.",
            "# TYPE pt_step_flops gauge",
            f"pt_step_flops {sflops:.6g}",
            "# TYPE pt_step_bytes gauge",
            f"pt_step_bytes {sbytes:.6g}",
            "# HELP pt_roofline_intensity FLOPs per HBM byte of the "
            "last measured step (compare against pt_roofline_ridge).",
            "# TYPE pt_roofline_intensity gauge",
            f"pt_roofline_intensity {inten:.6g}",
            "# HELP pt_roofline_ridge Device ridge point: peak FLOPs / "
            "peak HBM bandwidth; intensity below this is memory-bound.",
            "# TYPE pt_roofline_ridge gauge",
            f"pt_roofline_ridge {peak_flops / peak_bw:.6g}",
            "# TYPE pt_peak_flops_per_s gauge",
            f"pt_peak_flops_per_s {peak_flops:.6g}",
            "# TYPE pt_peak_hbm_bytes_per_s gauge",
            f"pt_peak_hbm_bytes_per_s {peak_bw:.6g}",
        ]
        out.append("# HELP pt_fn_flops XLA-counted FLOPs of one call "
                   "of this entry point (latest compiled signature).")
        out.append("# TYPE pt_fn_flops gauge")
        for name, flops, byts, hbm, issued in fn_rows:
            out.append(f'pt_fn_flops{{fn="{name}"}} {flops:.6g}')
        out.append("# TYPE pt_fn_bytes_accessed gauge")
        for name, flops, byts, hbm, issued in fn_rows:
            out.append(f'pt_fn_bytes_accessed{{fn="{name}"}} {byts:.6g}')
        out.append("# HELP pt_fn_hbm_bytes argument+output+temp HBM of "
                   "this entry point's executable.")
        out.append("# TYPE pt_fn_hbm_bytes gauge")
        for name, flops, byts, hbm, issued in fn_rows:
            out.append(f'pt_fn_hbm_bytes{{fn="{name}"}} {hbm}')
        out.append("# TYPE pt_fn_flops_issued_total counter")
        for name, flops, byts, hbm, issued in fn_rows:
            out.append(
                f'pt_fn_flops_issued_total{{fn="{name}"}} {issued:.6g}')
        return "\n".join(out) + "\n"

    def reset(self):
        with self._lock:
            self._by_sig.clear()
            self._fns.clear()
            self._win_flops = self._win_bytes = 0.0
            self.last_mfu = self.peak_mfu = 0.0
            self.last_step_flops = self.last_step_bytes = 0.0
            self.last_intensity = 0.0
            self.steps_measured = 0


class MemoryAccountant:
    """Device-memory snapshots: allocator stats where the backend has
    them (`memory_stats()` — None on CPU), plus a `jax.live_arrays()`
    walk bucketed by dtype/shape. The walk touches every undeleted
    buffer's metadata, so polls are rate-limited (`min_interval_s`)
    unless forced — scrapes, bench ends, and log_freq records force."""

    def __init__(self, min_interval_s=1.0, top_buckets=8):
        self._lock = threading.Lock()
        self.min_interval_s = float(min_interval_s)
        self.top_buckets = int(top_buckets)
        self._last = None
        self._last_t = 0.0
        self.live_peak_bytes = 0
        self.in_use_peak_bytes = 0

    def poll(self, force=False, record=True):
        """Take (or reuse) a snapshot; returns the snapshot dict."""
        now = time.monotonic()
        with self._lock:
            if (not force and self._last is not None
                    and now - self._last_t < self.min_interval_s):
                return self._last
        snap = self._take()
        with self._lock:
            self._last = snap
            self._last_t = now
            self.live_peak_bytes = max(self.live_peak_bytes,
                                       snap["live_bytes"])
            self.in_use_peak_bytes = max(self.in_use_peak_bytes,
                                         snap.get("bytes_in_use") or 0)
            snap["live_peak_bytes"] = self.live_peak_bytes
            if snap.get("bytes_in_use") is not None:
                snap["peak_bytes_in_use"] = max(
                    snap.get("peak_bytes_in_use") or 0,
                    self.in_use_peak_bytes)
        if record:
            from . import flight_recorder as _fr
            _fr.record("device.memory",
                       live_bytes=snap["live_bytes"],
                       live_arrays=snap["live_arrays"],
                       live_peak_bytes=snap["live_peak_bytes"],
                       bytes_in_use=snap.get("bytes_in_use"),
                       bytes_limit=snap.get("bytes_limit"))
        return snap

    def _take(self):
        snap = {"ts": time.time(), "live_bytes": 0, "live_arrays": 0,
                "by_bucket": [], "devices": [], "bytes_in_use": None,
                "peak_bytes_in_use": None, "bytes_limit": None}
        try:
            import jax
        except Exception:
            return snap
        # allocator stats (TPU/GPU backends; None on CPU — graceful)
        in_use = peak = limit = 0
        have_stats = False
        try:
            for d in jax.local_devices():
                stats = d.memory_stats()
                if not stats:
                    snap["devices"].append(
                        {"id": d.id, "platform": d.platform,
                         "memory_stats": None})
                    continue
                have_stats = True
                in_use += int(stats.get("bytes_in_use", 0))
                peak += int(stats.get("peak_bytes_in_use", 0))
                limit += int(stats.get("bytes_limit", 0))
                snap["devices"].append(
                    {"id": d.id, "platform": d.platform,
                     "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                     "peak_bytes_in_use":
                         int(stats.get("peak_bytes_in_use", 0)),
                     "bytes_limit": int(stats.get("bytes_limit", 0))})
        except Exception:
            pass
        if have_stats:
            snap["bytes_in_use"] = in_use
            snap["peak_bytes_in_use"] = peak
            snap["bytes_limit"] = limit
        # live-array walk: who holds the bytes, by dtype/shape bucket
        buckets = {}
        total = count = 0
        try:
            for a in jax.live_arrays():
                try:
                    n = int(a.nbytes)
                    key = f"{a.dtype}{tuple(a.shape)}"
                except Exception:
                    continue
                total += n
                count += 1
                b = buckets.get(key)
                buckets[key] = (b[0] + n, b[1] + 1) if b else (n, 1)
        except Exception:
            pass
        snap["live_bytes"] = total
        snap["live_arrays"] = count
        snap["by_bucket"] = [
            {"bucket": k, "bytes": v[0], "count": v[1]}
            for k, v in sorted(buckets.items(),
                               key=lambda kv: -kv[1][0])[:self.top_buckets]]
        return snap

    def snapshot(self):
        """Last poll (taking one if none exists yet)."""
        with self._lock:
            last = self._last
        return last if last is not None else self.poll(force=True)

    def render_prometheus(self, force_poll=True):
        snap = self.poll(force=force_poll) if force_poll \
            else self.snapshot()
        out = [
            "# HELP pt_device_live_bytes Bytes held by live (undeleted) "
            "device arrays.",
            "# TYPE pt_device_live_bytes gauge",
            f"pt_device_live_bytes {snap['live_bytes']}",
            "# TYPE pt_device_live_arrays gauge",
            f"pt_device_live_arrays {snap['live_arrays']}",
            "# HELP pt_device_live_peak_bytes High-water mark of "
            "pt_device_live_bytes across polls.",
            "# TYPE pt_device_live_peak_bytes gauge",
            f"pt_device_live_peak_bytes {snap['live_peak_bytes']}",
        ]
        if snap.get("bytes_in_use") is not None:
            out += [
                "# HELP pt_device_bytes_in_use Allocator bytes in use "
                "(sum over local devices; absent on CPU).",
                "# TYPE pt_device_bytes_in_use gauge",
                f"pt_device_bytes_in_use {snap['bytes_in_use']}",
                "# TYPE pt_device_peak_bytes_in_use gauge",
                f"pt_device_peak_bytes_in_use {snap['peak_bytes_in_use']}",
                "# TYPE pt_device_bytes_limit gauge",
                f"pt_device_bytes_limit {snap['bytes_limit']}",
            ]
        for b in snap["by_bucket"]:
            out.append(
                f'pt_device_live_bucket_bytes{{bucket="{b["bucket"]}"}} '
                f'{b["bytes"]}')
        return "\n".join(out) + "\n"

    def reset(self):
        with self._lock:
            self._last = None
            self._last_t = 0.0
            self.live_peak_bytes = 0
            self.in_use_peak_bytes = 0


COSTS = CostRegistry()
ACCOUNTANT = MemoryAccountant()


def note_step(elapsed_s):
    """Module-level shorthand: the serving pump's per-step MFU hook."""
    return COSTS.note_step(elapsed_s)


def snapshot():
    return {"cost": COSTS.snapshot(), "memory": ACCOUNTANT.snapshot()}


def render_prometheus():
    """Everything this module knows, Prometheus text — appended to the
    serving `/metrics` next to the compile exposition."""
    return COSTS.render_prometheus() + ACCOUNTANT.render_prometheus()


def reset():
    COSTS.reset()
    ACCOUNTANT.reset()
