"""Fleet observability (aux subsystem: observability).

The fleet plane (`serving/fleet.py`) runs one serving process per
host; every observability surface below it — trace context, flight
recorder, chrome traces, pulse rings — is strictly per-process. This
module holds the pure, transport-free pieces that turn those
per-process artifacts into ONE fleet-wide story on the rank-0 router:

  * `ClockSkewEstimator` — NTP-style per-peer offset estimation from
    the `(t_send, t_remote, t_recv)` triples every rpc round trip
    yields for free (`RpcAgent.on_clock_sample`). The raw offset of
    one exchange is `t_remote - (t_send + t_recv)/2`; its uncertainty
    is half the round trip net of the server's hold time. Offsets are
    EWMA-smoothed (`PT_FLEET_CLOCK_ALPHA`) so a single congested
    round trip cannot yank the timeline, and `rebase()` maps any
    remote wall-clock stamp onto the router's clock.
  * `stitch_fleet_trace` — merge per-process span sections into one
    chrome-tracing document: one process row (`pid`) per
    `replica@host` section, every remote timestamp skew-corrected
    through the section's offset, and flow arrows chaining each trace
    id's spans ACROSS processes in corrected start order — the
    request's rpc hop becomes a visible arrow instead of two
    unrelated rows.
  * `merge_flight_sections` — the `/debug/fleet/flightrecorder`
    payload: per-host flight-recorder sections plus one merged,
    skew-corrected chronological event list (`ts_fleet` on every
    event names the router-clock time).
  * `write_fleet_bundle` — one fleet capture bundle directory: a
    top-level `meta.json` (trigger, trace ids, per-peer clock
    offsets, roster) plus one subdirectory per host holding that
    worker's flight dump, pulse window, and request ring.
    `tools/ptdump.py bundle <dir>` renders it as a cross-host
    post-mortem narrative.

Pure stdlib, no sockets, no serving imports — the fleet plane feeds
sections in; everything here is arithmetic and JSON shaping, so it
unit-tests without a fleet.
"""
from __future__ import annotations

import json
import os
import threading
import time
import zlib

from .._env import env_float

__all__ = ["ClockSkewEstimator", "stitch_fleet_trace",
           "merge_flight_sections", "write_fleet_bundle"]


class ClockSkewEstimator:
    """EWMA-smoothed per-peer clock offset, fed one rpc round trip at
    a time. Thread-safe: samples arrive from whatever threads issue
    rpc calls (the heartbeat/obs pollers, scrape threads, dispatch).

    Sign convention: `offset(peer)` is how far the PEER's wall clock
    runs ahead of ours, so `rebase(peer, t_remote)` = `t_remote -
    offset(peer)` places a remote stamp on the local timeline.
    """

    def __init__(self, alpha=None):
        self.alpha = float(alpha if alpha is not None
                           else env_float("PT_FLEET_CLOCK_ALPHA"))
        self._lock = threading.Lock()
        self._peers = {}   # peer -> {"offset_s", "uncertainty_s", "samples"}

    def sample(self, peer, t_send, t_remote, t_recv, hold_s=0.0):
        """Fold one exchange into the estimate. `t_send`/`t_recv` are
        local wall stamps bracketing the round trip; `t_remote` the
        peer's wall stamp while it held the request; `hold_s` how long
        the peer held it (subtracted from the uncertainty bound).
        Returns the smoothed (offset_s, uncertainty_s)."""
        raw = float(t_remote) - (float(t_send) + float(t_recv)) / 2.0
        unc = max(float(t_recv) - float(t_send) - float(hold_s), 0.0) / 2.0
        a = self.alpha
        with self._lock:
            st = self._peers.get(peer)
            if st is None:
                st = {"offset_s": raw, "uncertainty_s": unc, "samples": 0}
                self._peers[peer] = st
            else:
                st["offset_s"] += a * (raw - st["offset_s"])
                st["uncertainty_s"] += a * (unc - st["uncertainty_s"])
            st["samples"] += 1
            return st["offset_s"], st["uncertainty_s"]

    def offset(self, peer):
        """Smoothed offset in seconds; 0.0 for a never-sampled peer
        (an uncorrected merge beats a refused one)."""
        with self._lock:
            st = self._peers.get(peer)
            return float(st["offset_s"]) if st else 0.0

    def uncertainty(self, peer):
        with self._lock:
            st = self._peers.get(peer)
            return float(st["uncertainty_s"]) if st else 0.0

    def rebase(self, peer, t):
        """A remote wall-clock stamp, expressed on the local clock."""
        return float(t) - self.offset(peer)

    def snapshot(self):
        with self._lock:
            return {p: dict(st) for p, st in self._peers.items()}


# ---------------------------------------------------------------------------
# cross-host trace stitching


def _flow_id(trace_id):
    return zlib.crc32(str(trace_id).encode()) & 0x7FFFFFFF


def stitch_fleet_trace(sections):
    """Merge per-process span sections into one chrome-tracing doc.

    `sections` is a list of dicts:

        {"label": "router" | "r0@hostA", "offset_s": 0.0,
         "spans": [span dicts (name/t_start/dur_s/trace_id/...)]}

    Each section becomes its own process row (pid = section index,
    process_name = label); inside a section each trace id gets a named
    thread row (row 0 = untraced). Every timestamp is rebased by the
    section's `offset_s` BEFORE merging, so one trace's spans order
    correctly across hosts with skewed clocks, and flow arrows chain
    each trace id's spans across all sections in corrected start
    order."""
    events = []
    meta = []
    per_trace = {}                  # trace_id -> [event index]
    for pid, sec in enumerate(sections):
        label = str(sec.get("label") or f"section{pid}")
        off = float(sec.get("offset_s") or 0.0)
        args = {"name": label}
        if sec.get("offset_s") is not None:
            args["clock_offset_s"] = off
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": args})
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": "untraced"}})
        tids = {}
        for sp in sec.get("spans") or []:
            trace_id = sp.get("trace_id")
            if trace_id is None:
                tid = 0
            else:
                tid = tids.get(trace_id)
                if tid is None:
                    tid = len(tids) + 1
                    tids[trace_id] = tid
                    meta.append({"name": "thread_name", "ph": "M",
                                 "pid": pid, "tid": tid,
                                 "args": {"name": f"trace {trace_id}"}})
            ev_args = dict(sp.get("args") or {})
            for k in ("trace_id", "span_id", "parent_id"):
                if sp.get(k) is not None:
                    ev_args[k] = sp[k]
            ev_args["section"] = label
            ev = {"name": sp["name"], "ph": "X", "pid": pid, "tid": tid,
                  "ts": (float(sp["t_start"]) - off) * 1e6,
                  "dur": float(sp["dur_s"]) * 1e6,
                  "args": ev_args}
            if trace_id is not None:
                per_trace.setdefault(trace_id, []).append(len(events))
            events.append(ev)
    # flows: one chain per trace id across ALL processes, in
    # skew-corrected start order — the rpc/bulk hop rendered as arrows.
    # Anchored at span STARTS (a start ts is inside its slice, so the
    # viewer still binds it): phase spans nest (request.queued encloses
    # prefill/decode), and a midpoint anchor would run a chain backward
    # through an enclosing span, breaking the monotone ordering the
    # corrected start sort establishes.
    flows = []
    for trace_id, idxs in per_trace.items():
        if len(idxs) < 2:
            continue
        idxs = sorted(idxs, key=lambda i: events[i]["ts"])
        fid = _flow_id(trace_id)
        first = events[idxs[0]]
        flows.append({"name": "trace", "cat": "fleet", "ph": "s",
                      "id": fid, "pid": first["pid"],
                      "tid": first["tid"], "ts": first["ts"]})
        for i in idxs[1:]:
            e = events[i]
            flows.append({"name": "trace", "cat": "fleet", "ph": "f",
                          "bp": "e", "id": fid, "pid": e["pid"],
                          "tid": e["tid"], "ts": e["ts"]})
    return {"traceEvents": meta + events + flows,
            "displayTimeUnit": "ms",
            "fleet": {"sections": [str(s.get("label")) for s in sections]}}


# ---------------------------------------------------------------------------
# merged flight-recorder dump


def merge_flight_sections(sections):
    """The `/debug/fleet/flightrecorder` payload: each section's full
    flight snapshot under its label, plus one merged chronological
    event list where every event carries its `source` label and a
    skew-corrected `ts_fleet` (router-clock seconds).

    `sections`: [{"label", "offset_s", "uncertainty_s", "flight":
    <flight_recorder snapshot>}]."""
    out_sections = {}
    merged = []
    for sec in sections:
        label = str(sec.get("label") or "?")
        off = float(sec.get("offset_s") or 0.0)
        flight = sec.get("flight") or {}
        out_sections[label] = {
            "offset_s": off,
            "uncertainty_s": float(sec.get("uncertainty_s") or 0.0),
            "pid": flight.get("pid"),
            "dropped": flight.get("dropped", 0),
            "events": flight.get("events") or [],
        }
        for ev in flight.get("events") or []:
            e = dict(ev)
            e["source"] = label
            e["ts_fleet"] = float(ev.get("ts", 0.0)) - off
            merged.append(e)
    merged.sort(key=lambda e: e["ts_fleet"])
    return {"fleet": True, "merged_at": time.time(),
            "sections": out_sections, "events": merged}


# ---------------------------------------------------------------------------
# fleet capture bundles


def _safe_label(label):
    return "".join(c if c.isalnum() or c in "@-_." else "_"
                   for c in str(label)) or "section"


def _write_json(path, doc):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def write_fleet_bundle(root, name, meta, sections):
    """Write ONE fleet capture bundle: `<root>/<name>/meta.json` plus
    one subdirectory per section (`router/`, `r0@hostA/`, ...) each
    holding that process's `flight.json` / `pulse.json` /
    `requests.json`. Every file lands atomically (tmp + replace), so
    a reader never sees a torn document. Returns the bundle path."""
    path = os.path.join(root, name)
    os.makedirs(path, exist_ok=True)
    roster = []
    for sec in sections:
        label = _safe_label(sec.get("label"))
        sub = os.path.join(path, label)
        os.makedirs(sub, exist_ok=True)
        roster.append({
            "label": label,
            "offset_s": float(sec.get("offset_s") or 0.0),
            "uncertainty_s": float(sec.get("uncertainty_s") or 0.0),
            "host": sec.get("host"),
            "replica_id": sec.get("replica_id"),
        })
        _write_json(os.path.join(sub, "flight.json"),
                    sec.get("flight") or {})
        _write_json(os.path.join(sub, "pulse.json"),
                    sec.get("pulse") or {})
        _write_json(os.path.join(sub, "requests.json"),
                    {"requests": sec.get("requests") or []})
    doc = dict(meta)
    doc["fleet"] = True
    doc["sections"] = roster
    _write_json(os.path.join(path, "meta.json"), doc)
    return path
