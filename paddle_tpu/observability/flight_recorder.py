"""Crash flight recorder (aux subsystem: observability).

A bounded ring of recent structured events — spans, compile/retrace
events, scheduler decisions, errors, step records — that can be dumped
as JSON at any moment: on demand (the serving server's
`/debug/flightrecorder`), on SIGTERM, or around a fault
(`faulthandler` is wired by `install()`). The point is that when a
serving process dies or stalls, the last few thousand events are
evidence on disk instead of vapor.

Reference: the paper stack's profiler host ring + the XLA "debug
flight recorder" idea; TPU retrace storms and host syncs are invisible
in aggregate metrics but obvious in the last N events.

Always cheap: `record()` is one dict build + deque append under a
lock. Disable entirely with PADDLE_TPU_FLIGHT=0.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time

from .._env import env_int, env_str

__all__ = ["FlightRecorder", "RECORDER", "record", "snapshot", "dump",
           "install", "thread_stacks"]

DEFAULT_CAPACITY = env_int("PADDLE_TPU_FLIGHT_EVENTS")


class FlightRecorder:
    def __init__(self, capacity=DEFAULT_CAPACITY, enabled=None):
        import collections
        self._ring = collections.deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._seq = 0
        self._dropped = 0
        if enabled is None:
            enabled = env_str("PADDLE_TPU_FLIGHT", "1") != "0"
        self.enabled = enabled
        self._installed = False
        self._prev_sigterm = None

    # -- recording (hot path) -----------------------------------------
    def record(self, kind, **fields):
        """Append one event. `fields` must be JSON-serializable."""
        if not self.enabled:
            return None
        ev = {"ts": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(ev)
        return ev

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._dropped = 0

    # -- reading -------------------------------------------------------
    def events(self, kind=None):
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def snapshot(self, reason="on_demand"):
        with self._lock:
            evs = list(self._ring)
            dropped = self._dropped
        return {
            "dumped_at": time.time(),
            "reason": reason,
            "pid": os.getpid(),
            "capacity": self._ring.maxlen,
            "dropped": dropped,
            "compile": _compile_totals(),
            "events": evs,
        }

    def dump(self, path=None, reason="on_demand"):
        """Write the snapshot as JSON; returns the path written."""
        if path is None:
            d = env_str("PADDLE_TPU_FLIGHT_DIR")
            path = os.path.join(
                d, f"pt_flightrecorder-{os.getpid()}.json")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(reason=reason), f)
        os.replace(tmp, path)
        return path

    # -- crash wiring --------------------------------------------------
    def install(self, dump_path=None, sigterm=True, fault=True):
        """Wire the recorder to process death: SIGTERM dumps the ring
        (then chains to the previous handler / default exit), and
        `faulthandler` is enabled so hard faults print every thread's
        stack. Main-thread only for the signal part (CPython rule);
        callers off the main thread just get faulthandler."""
        if self._installed:
            return False
        if fault:
            import faulthandler
            if not faulthandler.is_enabled():
                faulthandler.enable()
        if sigterm and threading.current_thread() is threading.main_thread():
            prev = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                try:
                    self.record("signal", signal="SIGTERM")
                    self.dump(dump_path, reason="SIGTERM")
                finally:
                    if callable(prev):
                        prev(signum, frame)
                    else:
                        signal.signal(signal.SIGTERM, signal.SIG_DFL)
                        signal.raise_signal(signal.SIGTERM)

            self._prev_sigterm = prev
            signal.signal(signal.SIGTERM, _on_term)
        self._installed = True
        return True


def thread_stacks():
    """Every live thread's current stack, formatted — the /debug/stacks
    payload (why is the pump wedged / who holds the lock)."""
    import sys
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} (ident {tid}) ---")
        out.extend(l.rstrip("\n")
                   for l in traceback.format_stack(frame))
    return "\n".join(out)


def _compile_totals():
    """Compile-telemetry rollup embedded in every dump (lazy import:
    the recorder must not pull jax in just to record events)."""
    try:
        from . import compile_telemetry
        return compile_telemetry.REGISTRY.totals()
    except Exception:  # pragma: no cover — partial teardown
        return {}


RECORDER = FlightRecorder()

# module-level conveniences bound to the global recorder
record = RECORDER.record
snapshot = RECORDER.snapshot
dump = RECORDER.dump
install = RECORDER.install
