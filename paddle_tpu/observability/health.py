"""Training-health monitoring (aux subsystem: observability).

Numerics checking in this stack predates jit-awareness:
`amp.debugging.check_numerics` pulled every tensor to host
(`np.asarray` + `int(bad.sum())` — exactly what tpulint TPL001
rejects inside traced code) and `utils.watchdog.check_finite` ran one
blocking `bool()` per pytree leaf. This module is the single
jit-safe implementation both now route through:

  * **traced helpers** (`nonfinite_count`, `health_stats`,
    `traced_check`) — pure jnp reductions, safe inside any jitted
    step function. `health_stats` fuses the whole per-step health
    vector — loss, non-finite grad count, grad global norm,
    param-update ratio — into a handful of device scalars computed
    IN the existing traced train step, so observing them costs one
    batched `device_get`, not a sync per tensor.
  * **TrainingHealthMonitor** — the host half: one `observe()` per
    step does that single transfer, updates the `pt_train_*`
    counters/gauges, and feeds the flight recorder + structured log
    when a step goes non-finite.
  * **NaN blame** (`nan_blame`) — on demand, reruns one forward with
    finite-probes hooked on every leaf sublayer and names the FIRST
    layer that produced non-finite output from finite input (the
    producer, not the victims downstream). One batched transfer for
    all probes.
  * **HEALTH** — module-global counters the GradScaler
    (`pt_amp_found_inf_total`) and eager loops (`note_host_loss`)
    also report into; rendered on `/metrics`.

Import cost: stdlib only at import time (jax inside functions).
"""
from __future__ import annotations

import math
import threading

__all__ = [
    "HEALTH", "HealthCounters", "TrainingHealthMonitor",
    "nonfinite_count", "health_stats", "traced_check",
    "nonfinite_report", "nan_blame", "note_host_loss",
    "snapshot", "render_prometheus", "reset",
]


def _float_leaves(tree):
    """Floating-point raw-array leaves of a pytree, Tensors unwrapped."""
    import jax
    import jax.numpy as jnp

    def unwrap(t):
        return t._value if hasattr(t, "_value") else t
    leaves = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(unwrap, tree,
                               is_leaf=lambda t: hasattr(t, "_value")))
    return [l for l in leaves
            if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)]


# ---------------------------------------------------------------------------
# traced-safe device-side reductions
# ---------------------------------------------------------------------------
def nonfinite_count(tree):
    """Total count of non-finite elements across all floating leaves —
    one fused reduction per array, one int32 scalar out. Traced-safe."""
    import jax.numpy as jnp
    leaves = _float_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.int32)
    total = jnp.zeros((), jnp.int32)
    for l in leaves:
        total = total + jnp.sum(
            ~jnp.isfinite(l.astype(jnp.float32))).astype(jnp.int32)
    return total


def _sumsq(leaves):
    import jax.numpy as jnp
    total = jnp.zeros((), jnp.float32)
    for l in leaves:
        lf = l.astype(jnp.float32)
        total = total + jnp.sum(lf * lf)
    return total


def health_stats(loss, grads=None, params=None, new_params=None):
    """The fused per-step health vector, computed INSIDE traced code:

      loss          — the step loss as f32
      nonfinite     — non-finite element count over loss + grads
      grad_norm     — global L2 norm of the gradients
      update_ratio  — ||new_params - params|| / ||params|| (optimizer
                      step size relative to weight scale; the classic
                      divergence early-warning)

    Returns a dict of device scalars — hand it to
    TrainingHealthMonitor.observe(), which does ONE batched transfer.
    """
    import jax.numpy as jnp
    lv = loss._value if hasattr(loss, "_value") else loss
    lv = jnp.asarray(lv, jnp.float32).reshape(())
    stats = {"loss": lv, "nonfinite": nonfinite_count(lv)}
    if grads is not None:
        gleaves = _float_leaves(grads)
        stats["nonfinite"] = stats["nonfinite"] + nonfinite_count(grads)
        stats["grad_norm"] = jnp.sqrt(_sumsq(gleaves))
    if params is not None and new_params is not None:
        pleaves = _float_leaves(params)
        nleaves = _float_leaves(new_params)
        diff = [n - p for p, n in zip(pleaves, nleaves)]
        psq = _sumsq(pleaves)
        stats["update_ratio"] = jnp.sqrt(_sumsq(diff)) / \
            jnp.sqrt(psq + jnp.float32(1e-12))
    return stats


def traced_check(value, name="tensor"):
    """Traced-code-safe numerics check: one fused isfinite reduction,
    surfaced through `jax.debug.callback` (async — no host sync on the
    step's critical path, tpulint-clean). A non-finite count increments
    `pt_train_nonfinite_total` and lands in the flight recorder; it
    cannot raise from inside the trace — attach a
    TrainingHealthMonitor(abort=True) host-side to turn counts into
    exceptions at the step boundary."""
    import functools

    import jax
    import jax.numpy as jnp
    bad = jnp.sum(~jnp.isfinite(jnp.asarray(value).astype(jnp.float32)))
    jax.debug.callback(
        functools.partial(_on_traced_count, name=name), bad)
    return value


def _on_traced_count(bad, name):
    n = int(bad)
    if n:
        HEALTH.note_nonfinite(n, where=name, source="traced_check")


def nonfinite_report(tree, names=None):
    """Host-side: per-leaf non-finite counts with ONE batched device
    transfer (replaces utils.watchdog's per-leaf bool() round trips).
    Returns [(index_or_name, count), ...] for offending leaves only."""
    import jax
    import jax.numpy as jnp
    leaves = _float_leaves(tree)
    if not leaves:
        return []
    counts = jax.device_get(
        jnp.stack([jnp.sum(~jnp.isfinite(l.astype(jnp.float32)))
                   for l in leaves]))
    out = []
    for i, c in enumerate(counts):
        if int(c):
            out.append((names[i] if names else i, int(c)))
    return out


# ---------------------------------------------------------------------------
# global counters (stdlib-only; rendered on /metrics)
# ---------------------------------------------------------------------------
class HealthCounters:
    def __init__(self):
        self._lock = threading.Lock()
        self.nonfinite_steps = 0       # observations with any bad value
        self.nonfinite_values = 0      # total bad elements seen
        self.found_inf_steps = 0       # GradScaler skipped steps
        self.last_loss = None
        self.last_grad_norm = None
        self.last_update_ratio = None
        self.last_blame = None

    def note_nonfinite(self, count, where="train", source="monitor",
                       **fields):
        with self._lock:
            self.nonfinite_steps += 1
            self.nonfinite_values += int(count)
        from . import flight_recorder as _fr
        from .logging import get_logger
        _fr.record("health", event="nonfinite", where=where,
                   source=source, count=int(count), **fields)
        get_logger("health").event(
            "health.nonfinite", level="warning", where=where,
            source=source, count=int(count), **fields)

    def note_found_inf(self, scale):
        with self._lock:
            self.found_inf_steps += 1
            self.nonfinite_steps += 1
        from . import flight_recorder as _fr
        from .logging import get_logger
        _fr.record("health", event="amp.found_inf", scale=float(scale))
        get_logger("health").event(
            "health.amp_found_inf", level="warning", scale=float(scale))

    def note_gauges(self, loss=None, grad_norm=None, update_ratio=None):
        with self._lock:
            if loss is not None:
                self.last_loss = float(loss)
            if grad_norm is not None:
                self.last_grad_norm = float(grad_norm)
            if update_ratio is not None:
                self.last_update_ratio = float(update_ratio)

    def snapshot(self):
        with self._lock:
            return {
                "nonfinite_steps": self.nonfinite_steps,
                "nonfinite_values": self.nonfinite_values,
                "found_inf_steps": self.found_inf_steps,
                "last_loss": self.last_loss,
                "last_grad_norm": self.last_grad_norm,
                "last_update_ratio": self.last_update_ratio,
                "last_blame": self.last_blame,
            }

    def render_prometheus(self):
        s = self.snapshot()
        out = [
            "# HELP pt_train_nonfinite_total Train-health observations "
            "that contained non-finite values (loss/grads/checks).",
            "# TYPE pt_train_nonfinite_total counter",
            f"pt_train_nonfinite_total {s['nonfinite_steps']}",
            "# TYPE pt_train_nonfinite_values_total counter",
            f"pt_train_nonfinite_values_total {s['nonfinite_values']}",
            "# HELP pt_amp_found_inf_total GradScaler steps skipped for "
            "inf/nan grads (dynamic loss scaling backed off).",
            "# TYPE pt_amp_found_inf_total counter",
            f"pt_amp_found_inf_total {s['found_inf_steps']}",
        ]
        for key, metric in (("last_loss", "pt_train_loss"),
                            ("last_grad_norm", "pt_train_grad_norm"),
                            ("last_update_ratio",
                             "pt_train_update_ratio")):
            v = s[key]
            if v is not None and math.isfinite(v):
                out.append(f"# TYPE {metric} gauge")
                out.append(f"{metric} {v:.6g}")
        return "\n".join(out) + "\n"

    def reset(self):
        with self._lock:
            self.nonfinite_steps = 0
            self.nonfinite_values = 0
            self.found_inf_steps = 0
            self.last_loss = None
            self.last_grad_norm = None
            self.last_update_ratio = None
            self.last_blame = None


HEALTH = HealthCounters()


def note_host_loss(value, where="train"):
    """Cheap eager-loop hook (hapi.Model.fit): `value` is already a
    host float — no device traffic. Counts a non-finite loss."""
    try:
        v = float(value)
    except (TypeError, ValueError):
        return
    HEALTH.note_gauges(loss=v)
    if not math.isfinite(v):
        HEALTH.note_nonfinite(1, where=where, source="host_loss")


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------
class TrainingHealthMonitor:
    """Host half of the per-step health check.

        monitor = TrainingHealthMonitor()
        # inside your traced step:  stats = health_stats(loss, grads,
        #                                                params, new_p)
        # after the step (host):    monitor.observe(stats, step=i)

    `observe` does ONE batched device_get of the fused scalars; a
    non-finite step bumps `pt_train_nonfinite_total`, lands in the
    flight recorder, and (with abort=True) raises FloatingPointError.
    """

    def __init__(self, name="train", abort=False, counters=None):
        self.name = name
        self.abort = abort
        self.counters = counters or HEALTH
        self.last = None

    stats = staticmethod(health_stats)

    def observe(self, stats, step=None):
        import jax
        vals = jax.device_get(stats)     # one batched transfer
        loss = float(vals.get("loss", 0.0))
        nonfinite = int(vals.get("nonfinite", 0))
        grad_norm = vals.get("grad_norm")
        update_ratio = vals.get("update_ratio")
        rec = {"loss": loss, "nonfinite": nonfinite, "step": step}
        if grad_norm is not None:
            rec["grad_norm"] = float(grad_norm)
        if update_ratio is not None:
            rec["update_ratio"] = float(update_ratio)
        self.last = rec
        self.counters.note_gauges(loss=loss, grad_norm=rec.get("grad_norm"),
                                  update_ratio=rec.get("update_ratio"))
        bad = nonfinite > 0 or not math.isfinite(loss)
        if bad:
            self.counters.note_nonfinite(
                max(nonfinite, 1), where=self.name, source="monitor",
                step=step, loss=loss)
            if self.abort:
                raise FloatingPointError(
                    f"health[{self.name}]: step {step} produced "
                    f"{nonfinite} non-finite values (loss={loss}); run "
                    "observability.health.nan_blame(model, *inputs) to "
                    "name the producing layer")
        return rec

    def blame(self, layer, *inputs, **kwargs):
        return nan_blame(layer, *inputs, **kwargs)


# ---------------------------------------------------------------------------
# NaN blame: name the first non-finite producer in the layer tree
# ---------------------------------------------------------------------------
def _finite_flag(tree):
    """Device scalar: True iff every floating leaf is entirely finite."""
    import jax.numpy as jnp
    leaves = _float_leaves(tree)
    ok = jnp.asarray(True)
    for l in leaves:
        ok = ok & jnp.all(jnp.isfinite(l.astype(jnp.float32)))
    return ok


def nan_blame(layer, *inputs, **kwargs):
    """Run ONE forward of `layer` with finite-probes on every leaf
    sublayer (and the root); return a dict naming the first sublayer —
    in execution order — whose output went non-finite while its inputs
    were still finite (i.e. the producer). Probes stay on device until
    a single batched transfer at the end.

    Returns None when the forward is clean; otherwise
    {"layer": name, "class": type name, "inputs_finite": bool}.
    A non-finite *network input* blames the first victim with
    inputs_finite=False, which tells you to look upstream of the net.
    """
    import jax
    probes = []          # (name, class, in_ok, out_ok) in call order
    hooks = []

    def make_hook(name, cls):
        def hook(l, inp, out):
            probes.append((name, cls, _finite_flag(inp),
                           _finite_flag(out)))
        return hook

    for name, sub in layer.named_sublayers(include_self=True):
        if next(iter(sub._sub_layers.values()), None) is not None:
            continue             # containers: probe leaves only
        hooks.append(sub.register_forward_post_hook(
            make_hook(name or type(sub).__name__, type(sub).__name__)))
    try:
        layer(*inputs, **kwargs)
    finally:
        for h in hooks:
            h.remove()
    if not probes:
        return None
    flags = jax.device_get([(p[2], p[3]) for p in probes])  # ONE transfer
    first_bad = None
    for (name, cls, _, _), (in_ok, out_ok) in zip(probes, flags):
        if not bool(out_ok):
            hit = {"layer": name, "class": cls,
                   "inputs_finite": bool(in_ok)}
            if bool(in_ok):
                first_bad = hit          # the producer — done
                break
            if first_bad is None:
                first_bad = hit          # victim; keep looking upstream
    if first_bad is not None:
        HEALTH.last_blame = first_bad["layer"]
        from . import flight_recorder as _fr
        _fr.record("health", event="nan_blame", **first_bad)
    return first_bad


# ---------------------------------------------------------------------------
# module-level exposition (mirrors compile_telemetry's shape)
# ---------------------------------------------------------------------------
def snapshot():
    return HEALTH.snapshot()


def render_prometheus():
    return HEALTH.render_prometheus()


def reset():
    HEALTH.reset()
