"""Structured JSON logging with rate limiting (aux: observability).

One event = one JSON line: `{"ts": ..., "logger": ..., "event": ...,
**fields}`. Every event also lands in the flight recorder (bounded
ring — always safe), while the *stream* emission is rate-limited per
event type so a hot loop (the serving pump logs every step) cannot
drown a terminal or a log shipper. Dropped-line counts are carried on
the next emitted line of that type, so the suppression is visible.

Streams: by default events go only to the flight recorder; set
PADDLE_TPU_LOG=1 to emit to stderr, PADDLE_TPU_LOG_FILE=<path> to
emit to a file, or pass an explicit `stream` (tests hand a StringIO).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

from .._env import env_str

__all__ = ["StructuredLogger", "RateLimiter", "get_logger"]


class RateLimiter:
    """Token bucket per key: `allow(key)` spends one token; buckets
    refill at `rate_per_s` up to `burst`."""

    def __init__(self, rate_per_s=20.0, burst=40):
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._lock = threading.Lock()
        self._state = {}            # key -> [tokens, last_ts]

    def allow(self, key, now=None):
        if self.rate <= 0:
            return True
        now = time.monotonic() if now is None else now
        with self._lock:
            tokens, last = self._state.get(key, (self.burst, now))
            tokens = min(self.burst, tokens + (now - last) * self.rate)
            ok = tokens >= 1.0
            if ok:
                tokens -= 1.0
            self._state[key] = (tokens, now)
            return ok


def _default_stream():
    path = env_str("PADDLE_TPU_LOG_FILE")
    if path:
        return open(path, "a", buffering=1)
    if env_str("PADDLE_TPU_LOG", "0") == "1":
        return sys.stderr
    return None


class StructuredLogger:
    def __init__(self, name, stream="auto", rate_per_s=20.0, burst=40,
                 recorder=None):
        self.name = name
        self.stream = _default_stream() if stream == "auto" else stream
        self._limiter = RateLimiter(rate_per_s, burst)
        self._lock = threading.Lock()
        self._dropped = {}          # event type -> suppressed count
        if recorder is None:
            from . import flight_recorder as _fr
            recorder = _fr.RECORDER
        self._recorder = recorder

    def event(self, event, level="info", **fields):
        """Emit one structured event. Returns True when the line
        reached the stream (False: no stream, or rate-limited —
        either way the flight recorder got it)."""
        self._recorder.record("log", event=event, level=level,
                              logger=self.name, **fields)
        if self.stream is None:
            return False
        if not self._limiter.allow(event):
            with self._lock:
                self._dropped[event] = self._dropped.get(event, 0) + 1
            return False
        rec = {"ts": round(time.time(), 6), "logger": self.name,
               "level": level, "event": event}
        rec.update(fields)
        with self._lock:
            dropped = self._dropped.pop(event, 0)
            if dropped:
                rec["rate_limited_dropped"] = dropped
            line = json.dumps(rec, default=str)
            try:
                self.stream.write(line + "\n")
            except Exception:       # a dead log pipe must not kill serving
                return False
        return True


_loggers = {}
_loggers_lock = threading.Lock()


def get_logger(name, **kwargs):
    """Process-wide logger cache; kwargs only apply on first creation."""
    with _loggers_lock:
        lg = _loggers.get(name)
        if lg is None:
            lg = _loggers[name] = StructuredLogger(name, **kwargs)
        return lg
