"""Telemetry pulse plane (aux subsystem: observability).

Every other surface in the stack is point-in-time: `/metrics` shows
cumulative counters, `/debug/flightrecorder` the last N events. This
module adds the *short-horizon history* an operator (or a post-mortem)
actually reads trends from:

  * `PulseRing`    — one bounded time-series of (wall_ts, value).
  * `PulseSampler` — derives a ring per signal **generically** from a
    `MetricsRegistry.snapshot()` dict: counters become per-second
    rates via deltas, gauges are sampled as-is, histograms become
    windowed p50/p99 computed from cumulative-bucket deltas between
    consecutive samples (so the percentiles describe the last
    interval, not the process lifetime). A `goodput_ratio` composite
    is derived from the pt_goodput_tokens / pt_tokens counter pair.
  * `PulsePlane`   — owns a sampler plus the trigger/capture logic:
    a daemon thread ticks every `PT_PULSE_INTERVAL_S` (scrapes also
    opportunistically sample, deduped by the same interval), and on a
    trigger — step-stall anomaly, engine restart, crash-loop breaker
    opening, or an SLO-violation burst — writes a rate-limited
    **capture bundle** to `PT_CAPTURE_DIR`: flight-recorder dump, the
    triggering window of every pulse ring, the recent-request
    timeline ring, the metrics snapshot, and a config/env
    fingerprint, all tagged with the trace ids in flight at the
    trigger. `tools/ptdump.py bundle <dir>` renders one as a
    post-mortem narrative; `tools/ptop.py` renders the live rings.

Zero device syncs by construction: everything here reads host-side
registry snapshots and host clocks — the serving stack's single
sanctioned sync (`ServingEngine._fetch_results`) is untouched, and
the sampler/bundle-writer functions sit in tpulint's TPL001 hot set
so a stray device pull can never hide in the observability plane.

Knobs (read at construction): `PT_SERVE_PULSE=0` disables the plane
entirely (no thread, token-identical outputs), `PT_PULSE_INTERVAL_S`
(default 1.0) the sample cadence, `PT_PULSE_DEPTH` (default 240) the
ring depth, `PT_CAPTURE_DIR` (unset = bundles off), `PT_CAPTURE_MAX`
(default 8 per process) + `PT_CAPTURE_MIN_S` (default 30) the bundle
rate limit, `PT_PULSE_SLO_BURST` (default 3) the violations-per-
interval burst threshold.
"""
from __future__ import annotations

import json
import math
import os
import sys
import threading
import time
from collections import deque

from . import flight_recorder as _flight
from .._env import env_float, env_int, env_str
from .logging import get_logger

__all__ = ["PulseRing", "PulseSampler", "PulsePlane", "TRIGGERS"]

TRIGGERS = ("step_stall", "engine_restart", "breaker_open", "slo_burst")

# counters whose per-interval delta fires a capture trigger
_TRIGGER_COUNTERS = {
    "pt_step_anomalies": "step_stall",
    "pt_engine_restarts": "engine_restart",
}


class PulseRing:
    """One bounded time-series: (wall_ts, value) pairs, newest last.
    Appends come from the sampler (under the sampler's lock); reads
    copy, so consumers never hold the lock while serializing."""

    __slots__ = ("_ring",)

    def __init__(self, depth):
        self._ring = deque(maxlen=int(depth))

    def append(self, t, v):
        self._ring.append((t, v))

    def window(self, since=None):
        """Points with ts >= since (all when None), as [[t, v], ...]."""
        if since is None:
            return [[t, v] for t, v in self._ring]
        return [[t, v] for t, v in self._ring if t >= since]

    def last(self):
        return self._ring[-1] if self._ring else None

    def __len__(self):
        return len(self._ring)


def _windowed_percentile(prev_buckets, cur_buckets, q):
    """Interpolated q-th percentile of the observations that landed
    BETWEEN two cumulative-bucket snapshots; (None, 0) when no new
    observations arrived. A percentile in the +Inf bucket returns the
    largest finite edge (a lower bound), mirroring Histogram."""
    prev_buckets = prev_buckets or {}
    bounds = sorted(
        (math.inf if k == "+Inf" else float(k), k) for k in cur_buckets)
    total = cur_buckets.get("+Inf", 0) - prev_buckets.get("+Inf", 0)
    if total <= 0:
        return None, 0
    target = total * q / 100.0
    lo = 0.0
    seen = 0
    for b, key in bounds:
        dcum = cur_buckets.get(key, 0) - prev_buckets.get(key, 0)
        if dcum >= target:
            if b == math.inf:
                return lo, total        # lower bound: largest finite edge
            n = dcum - seen
            if n <= 0:
                return b, total
            return lo + (b - lo) * (target - seen) / n, total
        seen = dcum
        if b != math.inf:
            lo = b
    return lo, total


class PulseSampler:
    """Derive bounded ring time-series from successive registry
    snapshots. Signal names are `<metric key>` for gauges,
    `<metric key>:rate` (per second) for counters, and
    `<metric key>:p50` / `:p99` (windowed) for histograms — the
    `signals=` query filter prefix-matches these."""

    def __init__(self, depth=None):
        if depth is None:
            depth = env_int("PT_PULSE_DEPTH")
        self.depth = max(int(depth), 2)
        self._lock = threading.Lock()
        self._rings = {}                # signal name -> PulseRing
        self._prev = None               # previous snapshot dict
        self._prev_t = None
        self._last_pct = {}             # histogram signal -> last value

    def _ring(self, name):
        r = self._rings.get(name)
        if r is None:
            r = PulseRing(self.depth)
            self._rings[name] = r
        return r

    def sample(self, snap, t=None):
        """Fold one registry snapshot into the rings. Pure host
        arithmetic over the snapshot dict — no device traffic, no
        metric-object access (the snapshot already copied under the
        registry's locks)."""
        if t is None:
            t = time.time()
        with self._lock:
            prev, prev_t = self._prev, self._prev_t
            dt = None if prev_t is None else max(t - prev_t, 1e-9)
            for key, m in snap.items():
                kind = m.get("type") if isinstance(m, dict) else None
                if kind == "gauge":
                    self._ring(key).append(t, float(m["value"]))
                elif kind == "counter":
                    if dt is None:
                        continue        # first sample: no delta yet
                    pm = prev.get(key)
                    base = float(pm["value"]) if pm else 0.0
                    rate = max(float(m["value"]) - base, 0.0) / dt
                    self._ring(f"{key}:rate").append(t, rate)
                elif kind == "histogram":
                    if dt is None:
                        continue    # first sample: no window yet
                    pm = prev.get(key)
                    for q, tag in ((50, "p50"), (99, "p99")):
                        name = f"{key}:{tag}"
                        v, n = _windowed_percentile(
                            (pm or {}).get("buckets"),
                            m.get("buckets", {}), q)
                        if n == 0:
                            # idle interval: carry the last computed
                            # value so the series stays dense
                            v = self._last_pct.get(name, 0.0)
                        else:
                            self._last_pct[name] = v
                        self._ring(name).append(t, v)
            self._goodput(snap, prev, t)
            self._prev, self._prev_t = snap, t
        return t

    def _goodput(self, snap, prev, t):
        """Composite: delta(goodput_tokens)/delta(total_tokens) over
        the interval; 1.0 while nothing completed (no evidence of
        badput)."""
        cur_t = snap.get("pt_tokens")
        cur_g = snap.get("pt_goodput_tokens")
        if cur_t is None or cur_g is None:
            return
        pt = (prev or {}).get("pt_tokens")
        pg = (prev or {}).get("pt_goodput_tokens")
        d_tot = float(cur_t["value"]) - (float(pt["value"]) if pt else 0.0)
        d_good = float(cur_g["value"]) - (float(pg["value"]) if pg else 0.0)
        ring = self._ring("goodput_ratio")
        if d_tot <= 0:
            last = ring.last()
            ring.append(t, last[1] if last else 1.0)
        else:
            ring.append(t, max(min(d_good / d_tot, 1.0), 0.0))

    def series(self, window=None, signals=None, now=None):
        """JSON-shaped view: {signal: [[t, v], ...]}. `window` trims to
        the last N seconds; `signals` is an iterable of name prefixes
        (a bare metric name selects all its derived signals)."""
        if now is None:
            now = time.time()
        since = None if not window else now - float(window)
        with self._lock:
            items = sorted(self._rings.items())
            out = {}
            for name, ring in items:
                if signals and not any(name.startswith(s)
                                       for s in signals):
                    continue
                pts = ring.window(since)
                if pts:
                    out[name] = pts
        return out


def _env_fingerprint():
    """The config/env half of a bundle: every PT_/PADDLE_TPU_/JAX_
    knob plus process identity — enough to answer 'what exactly was
    this process running' without the process."""
    env = {k: v for k, v in sorted(os.environ.items())
           if k.startswith(("PT_", "PADDLE_TPU_", "JAX_"))}
    return {"pid": os.getpid(), "argv": list(sys.argv),
            "python": sys.version.split()[0],
            "platform": sys.platform, "env": env}


class PulsePlane:
    """Sampler + trigger/capture logic for one scheduler (the Router
    aggregates per-replica planes through `RequestScheduler.pulse`).

    Callables are injected so this module imports nothing from
    serving/ (no cycle): `snapshot_fn()` returns the registry
    snapshot, `scan_fn()` runs scrape-side analysis first (the
    anomaly sentinel), `info_fn()` returns trigger-time context
    (trace ids in flight, breaker state), `recent_fn(n)` the recent-
    request ring, `self_cost_fn(dt)` books the pass's own cost
    (pt_scrape_self_seconds)."""

    def __init__(self, snapshot_fn, *, scan_fn=None, info_fn=None,
                 recent_fn=None, self_cost_fn=None, interval_s=None,
                 depth=None, capture_dir=None, capture_max=None,
                 capture_min_s=None, slo_burst=None, start_thread=True,
                 name="pt-pulse"):
        if interval_s is None:
            interval_s = env_float("PT_PULSE_INTERVAL_S")
        self.interval_s = max(float(interval_s), 0.01)
        self._snapshot_fn = snapshot_fn
        self._scan_fn = scan_fn
        self._info_fn = info_fn
        self._recent_fn = recent_fn
        self._self_cost_fn = self_cost_fn
        self.sampler = PulseSampler(depth=depth)
        if capture_dir is None:
            capture_dir = env_str("PT_CAPTURE_DIR") or None
        self.capture_dir = capture_dir
        self.capture_max = int(capture_max if capture_max is not None
                               else env_int("PT_CAPTURE_MAX"))
        self.capture_min_s = float(
            capture_min_s if capture_min_s is not None
            else env_float("PT_CAPTURE_MIN_S"))
        self.slo_burst = int(slo_burst if slo_burst is not None
                             else env_int("PT_PULSE_SLO_BURST"))
        self._log = get_logger("pulse")
        self._lock = threading.Lock()   # sample dedup + trigger state
        self._last_sample_t = 0.0
        self._trig_prev = None          # counter values at last check
        self._breaker_prev = False
        self.triggers = {k: 0 for k in TRIGGERS}    # fired (pre-limit)
        self.bundles = []               # paths written
        self._bundle_last_t = 0.0
        self._bundle_seq = 0
        self._stop = threading.Event()
        self._thread = None
        if start_thread:
            self._thread = threading.Thread(
                target=self._run, name=name, daemon=True)
            self._thread.start()

    # -- sampling ------------------------------------------------------
    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the plane must
                # never take the process down; evidence over purity
                self._log.event("pulse.error", level="error",
                                error=repr(e))

    def tick(self, scanned=False):
        """One sample + trigger pass. Pure host work: scrape-side
        analysis, one registry snapshot, ring appends, counter-delta
        trigger checks. Runs on the pulse thread (and, deduped, on
        whatever thread scrapes /metrics or /debug/pulse)."""
        t0 = time.perf_counter()
        if self._scan_fn is not None and not scanned:
            self._scan_fn()
        snap = self._snapshot_fn()
        now = self.sampler.sample(snap)
        with self._lock:
            self._last_sample_t = now
        self._check_triggers(snap)
        if self._self_cost_fn is not None:
            self._self_cost_fn(time.perf_counter() - t0)

    def maybe_sample(self, scanned=False):
        """Opportunistic sample from a scrape path: ticks only when at
        least one interval passed since the last sample (the scrape
        cadence rides for free, the daemon thread fills the gaps)."""
        with self._lock:
            due = time.time() - self._last_sample_t >= self.interval_s
        if due:
            self.tick(scanned=scanned)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s + 1.0)

    @property
    def thread_alive(self):
        return self._thread is not None and self._thread.is_alive()

    # -- exposure ------------------------------------------------------
    def payload(self, window=None, signals=None):
        """The /debug/pulse JSON body."""
        now = time.time()
        with self._lock:
            triggers = dict(self.triggers)
            bundles = list(self.bundles)
        return {
            "enabled": True,
            "now": now,
            "interval_s": self.interval_s,
            "depth": self.sampler.depth,
            "signals": self.sampler.series(window=window,
                                           signals=signals, now=now),
            "triggers": triggers,
            "bundles": bundles,
        }

    def trigger_state(self):
        """Light cross-host poll target: cumulative trigger fires,
        local bundle paths, and the trace ids in flight — no rings, no
        registry snapshot. The fleet plane diffs `triggers` between
        polls to fire ONE fleet-wide capture per incident."""
        info = self._info_fn() if self._info_fn is not None else {}
        with self._lock:
            return {"triggers": dict(self.triggers),
                    "bundles": list(self.bundles),
                    "trace_ids": list(info.get("trace_ids") or [])}

    # -- triggers + capture bundles -----------------------------------
    def _trigger_counts(self, snap):
        counts = {}
        for key, m in snap.items():
            if not isinstance(m, dict) or m.get("type") != "counter":
                continue
            base = key.partition("{")[0]
            if base in _TRIGGER_COUNTERS:
                counts[key] = float(m["value"])
            elif base == "pt_slo_violated":
                counts[key] = float(m["value"])
        return counts

    def _check_triggers(self, snap):
        info = self._info_fn() if self._info_fn is not None else {}
        breaker = bool(info.get("breaker_open"))
        counts = self._trigger_counts(snap)
        fired = []
        # the whole delta pass runs under the lock: tick() races itself
        # (pulse daemon vs. opportunistic scrape threads), and an
        # unlocked `triggers[trig] += 1` read-modify-write loses fires
        # exactly when two triggers coincide — the moment they matter.
        # Only the capture (file I/O) runs outside.
        with self._lock:
            prev = self._trig_prev
            self._trig_prev = counts
            breaker_prev, self._breaker_prev = self._breaker_prev, breaker
            if prev is None:
                return                  # first pass: baseline only
            slo_delta = 0.0
            for key, cur in counts.items():
                delta = cur - prev.get(key, 0.0)
                if delta <= 0:
                    continue
                base = key.partition("{")[0]
                if base == "pt_slo_violated":
                    slo_delta += delta
                else:
                    fired.append(_TRIGGER_COUNTERS[base])
            if slo_delta >= self.slo_burst:
                fired.append("slo_burst")
            if breaker and not breaker_prev:
                fired.append("breaker_open")
            for trig in fired:
                self.triggers[trig] += 1
        if fired:
            self._capture(fired[0], info, snap)

    def _rate_limited(self):
        now = time.monotonic()
        with self._lock:
            if self.capture_dir is None:
                return True
            if self._bundle_seq >= self.capture_max:
                return True
            if self.bundles and \
                    now - self._bundle_last_t < self.capture_min_s:
                return True
            self._bundle_last_t = now
            self._bundle_seq += 1
            return False

    def _capture(self, trigger, info, snap):
        if self._rate_limited():
            return None
        return self._write_bundle(trigger, info, snap)

    def _write_bundle(self, trigger, info, snap):
        """Write one capture bundle directory. Runs on the pulse (or a
        scrape) thread — never the pump; the only cost to the serving
        path is the registry locks the snapshot already took."""
        stamp = time.strftime("%Y%m%d-%H%M%S")
        with self._lock:
            seq = self._bundle_seq
            triggers_total = dict(self.triggers)
        name = f"bundle-{stamp}-{seq:03d}-{trigger}" \
               f"-{os.getpid()}"
        path = os.path.join(self.capture_dir, name)
        os.makedirs(path, exist_ok=True)
        trace_ids = list(info.get("trace_ids") or [])
        meta = {
            "trigger": trigger, "at": time.time(), "pid": os.getpid(),
            "trace_ids": trace_ids,
            "triggers_total": triggers_total,
            "info": {k: v for k, v in info.items() if k != "trace_ids"},
        }
        pulse_doc = self.payload()
        # the triggering window of every ring carries the trigger's
        # identity — a bundle's pulse.json is self-describing
        pulse_doc["trigger"] = meta
        docs = {
            "meta.json": meta,
            "flight.json": _flight.snapshot(
                reason=f"pulse:{trigger}"),
            "pulse.json": pulse_doc,
            "requests.json": {
                "requests": (self._recent_fn(64)
                             if self._recent_fn is not None else [])},
            "metrics.json": snap,
            "config.json": _env_fingerprint(),
        }
        for fname, doc in docs.items():
            tmp = os.path.join(path, f".{fname}.tmp")
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, os.path.join(path, fname))
        with self._lock:
            self.bundles.append(path)
        _flight.record("pulse.bundle", trigger=trigger, path=path,
                       trace_ids=trace_ids or None)
        self._log.event("pulse.bundle", level="warning",
                        trigger=trigger, path=path)
        return path
