"""Request-scoped trace context (aux subsystem: observability).

A contextvar-propagated trace id + span stack, so every span and
structured event recorded while handling a request carries that
request's identity — across the HTTP handler, the scheduler, and the
engine, without threading an argument through every call site.

Reference: the host tracer's thread-local event chain
(paddle/fluid/platform/profiler's RecordEvent nesting); OpenTelemetry
naming is used deliberately (trace id / span id / parent id) so dumps
read like any other tracing system's.

Thread caveat: `contextvars` do NOT cross thread boundaries on their
own. Objects that hop threads (a ServingRequest moving from the HTTP
handler thread to the scheduler pump) carry their trace id as plain
state and re-`bind()` it where work resumes.
"""
from __future__ import annotations

import contextvars
import itertools
import os
import time

__all__ = ["new_trace_id", "current_trace_id", "current_span_id",
           "bind", "span", "Span"]

_trace_id = contextvars.ContextVar("pt_trace_id", default=None)
_span_id = contextvars.ContextVar("pt_span_id", default=None)
_ids = itertools.count(1)


def new_trace_id(prefix="tr"):
    """Process-unique, human-greppable id: <prefix>-<pid>-<seq>."""
    return f"{prefix}-{os.getpid():x}-{next(_ids):06x}"


def new_span_id():
    return f"sp-{next(_ids):06x}"


def current_trace_id():
    return _trace_id.get()


def current_span_id():
    return _span_id.get()


class bind:
    """Bind a trace id for the dynamic extent of a with-block (or via
    explicit .attach()/.detach() when the extent is not lexical, e.g.
    around one request's share of a pump iteration).

    `parent_span` seats an inbound parent span id too, so spans opened
    inside the extent nest under a REMOTE caller's span — this is how
    a cross-host rpc hop keeps one parent/child chain."""

    def __init__(self, trace_id, parent_span=None):
        self.trace_id = trace_id
        self.parent_span = parent_span
        self._token = None
        self._span_token = None

    def attach(self):
        self._token = _trace_id.set(self.trace_id)
        if self.parent_span is not None:
            self._span_token = _span_id.set(self.parent_span)
        return self

    def detach(self):
        if self._span_token is not None:
            _span_id.reset(self._span_token)
            self._span_token = None
        if self._token is not None:
            _trace_id.reset(self._token)
            self._token = None

    def __enter__(self):
        self.attach()
        return self.trace_id

    def __exit__(self, *exc):
        self.detach()
        return False


class Span:
    """One finished span: name + wall-clock placement + identity."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t_start",
                 "dur_s", "args")

    def __init__(self, name, trace_id, span_id, parent_id, t_start,
                 dur_s, args=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t_start = t_start      # time.time() epoch seconds
        self.dur_s = dur_s
        self.args = args

    def to_dict(self):
        d = {"name": self.name, "trace_id": self.trace_id,
             "span_id": self.span_id, "parent_id": self.parent_id,
             "t_start": self.t_start, "dur_s": self.dur_s}
        if self.args:
            d["args"] = dict(self.args)
        return d


def record_span_event(name, dur_s, *, trace_id=None, t_end=None,
                      args=None, parent_id=None, span_id=None):
    """Record an already-measured span (no with-block) into the sinks:
    the host trace ring (when tracing is enabled) and the flight
    recorder (always; its ring is bounded). Used for phase spans whose
    start/stop straddle threads — e.g. a request's queued/prefill/
    decode phases, assembled from timestamps at finalize time."""
    sp = Span(name, trace_id or current_trace_id(),
              span_id or new_span_id(), parent_id,
              (t_end if t_end is not None else time.time()) - dur_s,
              dur_s, args)
    _emit(sp)
    return sp


def _emit(sp: Span):
    from ..utils import trace as _trace
    if _trace.enabled():
        _trace.record(sp.name, sp.dur_s, None, trace_id=sp.trace_id,
                      span_id=sp.span_id, parent_id=sp.parent_id,
                      args=sp.args, ts_end=sp.t_start + sp.dur_s)
    from . import flight_recorder as _fr
    _fr.record("span", **sp.to_dict())


class span:
    """A live span as a with-block: nests under the current span (the
    parent/child chain rides the contextvar), stamps the current trace
    id, and on exit feeds the trace ring + flight recorder.

        with trace_context.span("scheduler.feed", args={"n": 3}):
            ...
    """

    def __init__(self, name, trace_id=None, args=None):
        self.name = name
        self._explicit_trace = trace_id
        self.args = args
        self.result = None
        self._t0 = None
        self._tok = None

    def __enter__(self):
        self.parent_id = _span_id.get()
        self.span_id = new_span_id()
        self._tok = _span_id.set(self.span_id)
        self._t0 = time.perf_counter()
        self._w0 = time.time()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        _span_id.reset(self._tok)
        args = self.args
        if exc_type is not None:
            args = dict(args or {})
            args["error"] = exc_type.__name__
        self.result = Span(
            self.name, self._explicit_trace or _trace_id.get(),
            self.span_id, self.parent_id, self._w0, dur, args)
        _emit(self.result)
        return False
