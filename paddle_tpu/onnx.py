"""paddle.onnx parity shim.

ONNX export is a GPU/CPU-deployment path; the TPU deployment story is
XLA AOT (jax.export → StableHLO), exposed here as export_stablehlo.
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export is not part of the TPU build; use "
        "paddle_tpu.onnx.export_stablehlo for XLA-AOT deployment")


def export_stablehlo(layer, path, example_inputs):
    """Serialize the layer's forward as StableHLO via jax.export."""
    import jax
    from jax import export as jexport
    from ._core.tensor import Tensor, unwrap

    params, buffers = layer.functional_state()

    def pure(params, *raws):
        wrapped = [Tensor(r) for r in raws]
        with layer._swapped_state(params, buffers):
            out = layer(*wrapped)
        return jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    raws = tuple(unwrap(a) for a in example_inputs)
    exported = jexport.export(jax.jit(pure))(params, *raws)
    data = exported.serialize()
    with open(path, "wb") as f:
        f.write(data)
    return path


def load_stablehlo(path):
    from jax import export as jexport
    with open(path, "rb") as f:
        return jexport.deserialize(f.read())
