"""paddle_tpu.ops: pallas TPU kernels + fused ops.

This package is the TPU analogue of the reference's hand-written CUDA
kernel library (paddle/phi/kernels/fusion/*): only ops where XLA fusion
isn't enough get custom kernels — attention family, MoE dispatch, RoPE.
"""
from .flash_attention import (  # noqa: F401
    flash_attention, flash_attention_bhsd, mha_reference,
)
from .rope import apply_rotary_emb, rope_cos_sin  # noqa: F401
from .fused import fused_rms_norm, fused_swiglu, fused_dropout_add  # noqa: F401
from .paged_attention import (  # noqa: F401
    paged_attention, paged_attention_reference, PagedKVCache,
)
