"""Flash attention for TPU — pallas kernels (fwd + bwd).

Replaces the reference's CUDA flash-attn integration
(paddle/phi/kernels/gpu/flash_attn_kernel.cu,
python/paddle/nn/functional/flash_attention.py) with a TPU-native
blockwise online-softmax kernel:

  * forward: grid (batch*heads, q_blocks, k_blocks); fp32 running
    (m, l, acc) scratch in VMEM persists across the sequential k grid
    dimension; saves per-row logsumexp L for the backward.
  * backward: one pass for dQ (grid over q), one for dK/dV (grid over
    k), both recomputing P = exp(QKᵀ·scale − L) block-wise — O(S) memory.
  * causal masking skips fully-masked k blocks via @pl.when predication.

Falls back to a pure-XLA reference implementation off-TPU (and for
features the kernel doesn't cover: arbitrary masks, dropout).
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

# (8,128)-aligned tile sizes; overridable for on-chip tuning sweeps.
# Canonical defaults live in _tuning_defaults (shared with autotune +
# perf guard so dedup/grouping stay in sync with the kernel).
from paddle_tpu._tuning_defaults import flash_block_q, flash_block_k
DEFAULT_BLOCK_Q = flash_block_q()
DEFAULT_BLOCK_K = flash_block_k()
# np.float32: a bare Python float lowers as an f64 constant inside Mosaic,
# and v5e libtpu rejects 'tpu.truncf f64->f32' — keep all kernel consts f32.
NEG_INF = np.float32(-1e30)
# index-map constants likewise must be i32: under jax_enable_x64 a literal 0
# traces as i64 and Mosaic fails to legalize the index-map func.return.
Z = np.int32(0)
LANES = 128  # TPU lane width: per-row stats are stored replicated over lanes
             # so every ref block keeps last-two dims (÷8, ÷128)-aligned


def _fit_lanes(x128, n):
    """(rows, 128) lane-replicated stat → (rows, n) for math against an
    (rows, n) tile. Values are equal across lanes, so slice or tile."""
    if n == LANES:
        return x128
    if n < LANES:
        return x128[:, :n]
    assert n % LANES == 0, f"block dim {n} must be a multiple of {LANES}"
    return jnp.tile(x128, (1, n // LANES))


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def pallas_disabled() -> bool:
    """Escape hatch: PT_DISABLE_PALLAS=1 forces the XLA reference path
    (e.g. when a new TPU generation rejects the kernel's block shapes)."""
    return os.environ.get("PT_DISABLE_PALLAS", "0") == "1"


# ---------------------------------------------------------------------------
# Reference (pure XLA) implementation — correctness baseline + fallback.
# ---------------------------------------------------------------------------
def mha_reference(q, k, v, bias=None, causal=False, sm_scale=None):
    """q,k,v: (B, H, S, D). Returns (out, logsumexp)."""
    *_, sq, d = q.shape
    sk = k.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o.astype(q.dtype), lse


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _col_mask(start, block, total, d):
    """(block, d) bool mask: rows of this block that are inside `total`."""
    idx = start + jax.lax.broadcasted_iota(jnp.int32, (block, d), 0)
    return idx < total


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                scale, causal, block_q, block_k, n_k, sq, sk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def body():
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]
        d = q.shape[-1]
        if sk % block_k != 0:
            km = _col_mask(ki * block_k, block_k, sk, d)
            k = jnp.where(km, k, 0.0)
            v = jnp.where(km, v, 0.0)
        if sq % block_q != 0:
            q = jnp.where(_col_mask(qi * block_q, block_q, sq, d), q, 0.0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = cols < sk
        if causal:
            valid = valid & (rows >= cols)
        if causal or sk % block_k != 0:
            s = jnp.where(valid, s, NEG_INF)
        m_prev = m_ref[:]                       # (block_q, LANES) replicated
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)          # (block_q, 1)
        m_new = jnp.maximum(m_prev, m_cur)                 # (block_q, LANES)
        p = jnp.exp(s - _fit_lanes(m_new, s.shape[-1]))
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * _fit_lanes(alpha, d) + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new
        l_ref[:] = l_new

    if causal:
        # skip blocks fully above the diagonal
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        d = o_ref.shape[-1]
        o_ref[0] = (acc_ref[:] / _fit_lanes(l_safe, d)).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(l_safe)


def _fwd_pallas(q, k, v, causal, scale, block_q, block_k, interpret):
    scale = np.float32(scale)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    n_q = pl.cdiv(sq, block_q)
    n_k = pl.cdiv(sk, block_k)
    bh = b * h
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, n_k=n_k,
                               sq=sq, sk=sk)
    mem = pltpu.VMEM if _HAS_PLTPU else None
    spec = lambda bs, im: pl.BlockSpec(bs, im, memory_space=mem) if mem else \
        pl.BlockSpec(bs, im)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            spec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, Z)),
            spec((1, block_k, d), lambda bh_, qi, ki: (bh_, ki, Z)),
            spec((1, block_k, d), lambda bh_, qi, ki: (bh_, ki, Z)),
        ],
        out_specs=[
            spec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, Z)),
            spec((1, block_q, LANES), lambda bh_, qi, ki: (bh_, qi, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            # per-row logsumexp replicated over the lane dim (TPU block rule:
            # last two dims of a block must be ÷8 / ÷128 or whole-array)
            jax.ShapeDtypeStruct((bh, sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return o.reshape(b, h, sq, d), lse[..., 0].reshape(b, h, sq)


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, block_q, block_k, n_k, sq, sk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        d = q.shape[-1]
        if sk % block_k != 0:
            km = _col_mask(ki * block_k, block_k, sk, d)
            k = jnp.where(km, k, 0.0)
            v = jnp.where(km, v, 0.0)
        if sq % block_q != 0:
            q = jnp.where(_col_mask(qi * block_q, block_q, sq, d), q, 0.0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = cols < sk
        if causal:
            valid = valid & (rows >= cols)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - _fit_lanes(lse_ref[0], s.shape[-1]))
        p = jnp.where(valid, p, 0.0)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        # mask after the product: OOB rows of the ragged final q block read
        # undefined lse/delta, and 0 * inf would poison the accumulator
        ds = jnp.where(valid,
                       p * (dp - _fit_lanes(delta_ref[0], dp.shape[-1])) * scale,
                       0.0)
        dq_acc[:] += jax.lax.dot_general(ds, k.astype(jnp.float32),
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(ki == n_k - 1)
    def _fin():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, n_q, sq, sk):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        d = q.shape[-1]
        if sk % block_k != 0:
            km = _col_mask(ki * block_k, block_k, sk, d)
            k = jnp.where(km, k, 0.0)
            v = jnp.where(km, v, 0.0)
        qm = None
        if sq % block_q != 0:
            qm = _col_mask(qi * block_q, block_q, sq, d)
            q = jnp.where(qm, q, 0.0)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = (cols < sk) & (rows < sq)
        if causal:
            valid = valid & (rows >= cols)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - _fit_lanes(lse_ref[0], s.shape[-1]))  # (bq, bk)
        p = jnp.where(valid, p, 0.0)
        do = do_ref[0].astype(jnp.float32)
        if qm is not None:
            do = jnp.where(qm, do, 0.0)
        dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = jnp.where(valid,
                       p * (dp - _fit_lanes(delta_ref[0], dp.shape[-1])) * scale,
                       0.0)
        dk_acc[:] += jax.lax.dot_general(ds, q.astype(jnp.float32),
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(qi == n_q - 1)
    def _fin():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, o, lse, do, causal, scale, block_q, block_k, interpret):
    scale = np.float32(scale)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    n_q = pl.cdiv(sq, block_q)
    n_k = pl.cdiv(sk, block_k)
    bh = b * h
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    qr, kr, vr = (t.reshape(bh, -1, d) for t in (q, k, v))
    dor = do.reshape(bh, sq, d)
    # lane-replicate per-row stats so their blocks obey the TPU (÷8, ÷128) rule
    lser = jnp.broadcast_to(lse.reshape(bh, sq)[..., None], (bh, sq, LANES))
    deltar = jnp.broadcast_to(delta.reshape(bh, sq)[..., None], (bh, sq, LANES))

    mem = pltpu.VMEM if _HAS_PLTPU else None
    spec = lambda bs, im: pl.BlockSpec(bs, im, memory_space=mem) if mem else \
        pl.BlockSpec(bs, im)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_k=n_k,
                          sq=sq, sk=sk),
        grid=(bh, n_q, n_k),
        in_specs=[
            spec((1, block_q, d), lambda b_, qi, ki: (b_, qi, Z)),
            spec((1, block_k, d), lambda b_, qi, ki: (b_, ki, Z)),
            spec((1, block_k, d), lambda b_, qi, ki: (b_, ki, Z)),
            spec((1, block_q, d), lambda b_, qi, ki: (b_, qi, Z)),
            spec((1, block_q, LANES), lambda b_, qi, ki: (b_, qi, Z)),
            spec((1, block_q, LANES), lambda b_, qi, ki: (b_, qi, Z)),
        ],
        out_specs=[spec((1, block_q, d), lambda b_, qi, ki: (b_, qi, Z))],
        out_shape=[jax.ShapeDtypeStruct((bh, sq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)] if _HAS_PLTPU else [],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)[0]

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_q=n_q,
                          sq=sq, sk=sk),
        grid=(bh, n_k, n_q),
        in_specs=[
            spec((1, block_q, d), lambda b_, ki, qi: (b_, qi, Z)),
            spec((1, block_k, d), lambda b_, ki, qi: (b_, ki, Z)),
            spec((1, block_k, d), lambda b_, ki, qi: (b_, ki, Z)),
            spec((1, block_q, d), lambda b_, ki, qi: (b_, qi, Z)),
            spec((1, block_q, LANES), lambda b_, ki, qi: (b_, qi, Z)),
            spec((1, block_q, LANES), lambda b_, ki, qi: (b_, qi, Z)),
        ],
        out_specs=[
            spec((1, block_k, d), lambda b_, ki, qi: (b_, ki, Z)),
            spec((1, block_k, d), lambda b_, ki, qi: (b_, ki, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ] if _HAS_PLTPU else [],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# Public op with custom VJP
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_mha(q, k, v, causal, scale, block_q, block_k, interpret):
    o, _ = _fwd_pallas(q, k, v, causal, scale, block_q, block_k, interpret)
    return o


def _flash_mha_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _fwd_pallas(q, k, v, causal, scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_mha_bwd(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd_pallas(q, k, v, o, lse, do, causal, scale, block_q,
                             block_k, interpret)
    return dq, dk, dv


_flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)


def flash_attention_bhsd(q, k, v, causal=False, sm_scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         use_pallas=None, interpret=None):
    """Core entry: q,k,v (B,H,S,D) → (B,H,S,D).

    use_pallas defaults to True on TPU; off-TPU uses the XLA reference
    (pallas interpret mode is available for kernel tests via interpret=True).
    """
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if use_pallas is None:
        use_pallas = _on_tpu() and not pallas_disabled()
    if interpret is None:
        interpret = not _on_tpu()
    if not use_pallas:
        o, _ = mha_reference(q, k, v, None, causal, scale)
        return o
    return _flash_mha(q, k, v, causal, scale, block_q, block_k, interpret)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, sm_scale=None, training=True,
                    use_pallas=None, **kwargs):
    """Paddle-compatible surface: q,k,v (B, S, H, D) like
    python/paddle/nn/functional/flash_attention.py. Returns (out, None).
    """
    q = jnp.swapaxes(query, 1, 2)
    k = jnp.swapaxes(key, 1, 2)
    v = jnp.swapaxes(value, 1, 2)
    # GQA: repeat kv heads if fewer than q heads
    hq, hk = q.shape[1], k.shape[1]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if dropout > 0.0 and training:
        # reference kernel drops attention *probabilities* (each output is
        # a partial sum over surviving keys), not whole outputs; no
        # in-kernel PRNG, so materialize P on the XLA path
        from .._core.state import prng
        *_, sq, d = q.shape
        sk = k.shape[-2]
        scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        if causal:
            cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            s = jnp.where(cm, s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        keep = jax.random.bernoulli(prng.next_key(), 1.0 - dropout, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout), 0.0)
        o = jnp.einsum("bhqk,bhkd->bhqd", p,
                       v.astype(jnp.float32)).astype(q.dtype)
    else:
        o = flash_attention_bhsd(q, k, v, causal=causal, sm_scale=sm_scale,
                                 use_pallas=use_pallas)
    out = jnp.swapaxes(o, 1, 2)
    return (out, None) if not return_softmax else (out, None, None)
