"""FlashMask attention for TPU — pallas kernels (fwd + bwd).

Reference: python/paddle/nn/functional/flash_attention.py:1299
(flashmask_attention) and its CUDA kernel
paddle/phi/kernels/gpu/flash_attn_kernel.cu — sparse causal masks
expressed as per-key-column start/end row indices, applied WITHOUT ever
materializing the dense (S, S) mask.

TPU-native design (VERDICT r2 item 4): the dense flash kernel's
blockwise online-softmax structure, plus

  * the column index vector `startend_row_indices` (B, Hk, S_k, n) is
    transposed to (n, S_k) per head and streamed block-by-block next to
    K/V — O(S) memory, never (S, S);
  * per (q-block, k-block), block-level aggregates (max of starts, min
    of ends over the k-block's columns) decide SKIP: a block whose every
    (row, col) pair is masked is skipped via @pl.when before any MXU
    work, mirroring the reference kernel's block-skip. Aggregates over
    the ragged tail's padding lanes only weaken the skip predicate
    (max grows / min shrinks), never falsify it;
  * surviving blocks apply the exact per-pair mask built from row iota
    vs the streamed start/end columns.

Mask semantics (n = trailing dim of startend_row_indices), matching the
reference docstring:
  causal,  n=1: masked  <=>  r >= start_j
  causal,  n=2: masked  <=>  start_j <= r < end_j
  ~causal, n=2: masked  <=>  (r >= start_j) | (r < end_j)
  ~causal, n=4: masked  <=>  (s0_j <= r < e0_j) | (s1_j <= r < e1_j)
plus the base causal triangle / sliding window when requested.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .flash_attention import (_HAS_PLTPU, pltpu, NEG_INF, Z, LANES,
                              _col_mask, _fit_lanes, _on_tpu,
                              pallas_disabled, DEFAULT_BLOCK_Q,
                              DEFAULT_BLOCK_K)


def _zero_oob(qi, ki, q, k, v, do=None, *, block_q, block_k, sq, sk):
    """Zero out ragged-tail garbage: OOB lanes of a padded block read
    undefined values, and 0 * NaN would poison the accumulators even
    where the keep-mask already zeroes p/ds."""
    d = q.shape[-1]
    if sk % block_k != 0:
        km = _col_mask(ki * block_k, block_k, sk, d)
        k = jnp.where(km, k, 0.0)
        v = jnp.where(km, v, 0.0)
    if sq % block_q != 0:
        qm = _col_mask(qi * block_q, block_q, sq, d)
        q = jnp.where(qm, q, 0.0)
        if do is not None:
            do = jnp.where(qm, do, 0.0)
    return (q, k, v) if do is None else (q, k, v, do)


def dropout_keep_mask(rows, cols, bh, seed, dropout):
    """Deterministic counter-based dropout keep-mask (True = keep).

    A murmur3-finalizer hash of the ABSOLUTE (row, col, batch*head,
    seed) coordinates, in plain uint32 jnp ops — no PRNG primitive, so
    the exact same mask is regenerated inside the pallas forward and
    both backward kernels (and by the dense reference) from coordinates
    alone. Reference parity: the CUDA kernel's philox dropout
    (flash_attn_kernel.cu) is likewise counter-based per position.

    rows/cols/bh: broadcastable int arrays; seed: int32 scalar;
    dropout: static python float in [0, 1).
    """
    x = (rows.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)) ^ \
        (cols.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)) ^ \
        (jnp.asarray(bh).astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)) ^ \
        jnp.asarray(seed).astype(jnp.uint32)
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> jnp.uint32(16))
    thresh = np.uint32(min(int(float(dropout) * 4294967296.0), 4294967295))
    return x >= thresh


def _sri_masked(rows, srib, causal, n):
    """(block_q, block_k) bool: pairs masked by the start/end indices.
    rows: (block_q, block_k) absolute row ids; srib: (n, block_k)."""
    def col(i):
        return srib[i:i + 1, :]  # (1, block_k) broadcasts over rows
    if causal and n == 1:
        return rows >= col(0)
    if causal and n == 2:
        return (rows >= col(0)) & (rows < col(1))
    if not causal and n == 2:
        return (rows >= col(0)) | (rows < col(1))
    if not causal and n == 4:
        return ((rows >= col(0)) & (rows < col(1))) | \
               ((rows >= col(2)) & (rows < col(3)))
    raise ValueError(f"startend_row_indices last dim {n} invalid for "
                     f"causal={causal}")


def _sri_all_masked(r_first, r_last, srib, causal, n):
    """Scalar bool: every (row, col) pair of this block is masked —
    safe to skip. Conservative under ragged-tail padding garbage in
    srib (max only grows, min only shrinks)."""
    def mx(i):
        return jnp.max(srib[i:i + 1, :])
    def mn(i):
        return jnp.min(srib[i:i + 1, :])
    if causal and n == 1:
        return r_first >= mx(0)
    if causal and n == 2:
        return (r_first >= mx(0)) & (r_last < mn(1))
    if not causal and n == 2:
        return (r_first >= mx(0)) | (r_last < mn(1))
    if not causal and n == 4:
        return ((r_first >= mx(0)) & (r_last < mn(1))) | \
               ((r_first >= mx(2)) & (r_last < mn(3)))
    raise ValueError(f"n={n} invalid for causal={causal}")


def _block_keep(qi, ki, block_q, block_k, sq, sk, causal, window, srib, n):
    """(compute_predicate, per-pair keep mask builder) for one block."""
    r_first = qi * block_q
    r_last = qi * block_q + block_q - 1
    c_first = ki * block_k
    c_last = ki * block_k + block_k - 1
    compute = jnp.bool_(True)
    if causal:
        compute = compute & (r_last >= c_first)
    if window is not None:
        compute = compute & (c_last >= r_first - window[0])
        if not causal:
            compute = compute & (c_first <= r_last + window[1])
    if srib is not None:
        compute = compute & ~_sri_all_masked(r_first, r_last, srib,
                                             causal, n)

    def keep_mask():
        rows = r_first + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = c_first + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        keep = (cols < sk) & (rows < sq)
        if causal:
            keep = keep & (rows >= cols)
        if window is not None:
            keep = keep & (cols >= rows - window[0])
            if not causal:
                keep = keep & (cols <= rows + window[1])
        if srib is not None:
            keep = keep & ~_sri_masked(rows, srib, causal, n)
        return keep
    return compute, keep_mask


# ---------------------------------------------------------------------------
# Reference (dense XLA) — correctness baseline + off-TPU fallback.
# ---------------------------------------------------------------------------
def flashmask_reference(q, k, v, sri=None, causal=True, window=None,
                        sm_scale=None, dropout=0.0, dropout_seed=None):
    """q,k,v (B,H,S,D); sri (B,H,S_k,n) already at q heads. Returns
    (out, lse). Materializes the dense mask — baseline only. window may
    be an int (symmetric) or (left, right). dropout drops attention
    probabilities (reference kernel semantics) using the SAME
    counter-based mask the pallas kernels regenerate in-kernel
    (dropout_keep_mask) — exact fwd/bwd agreement with the kernel
    path."""
    *_, sq, d = q.shape
    sk = k.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if window is not None and np.isscalar(window):
        window = (int(window), int(window))
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    rows = jnp.arange(sq)[:, None]
    cols = jnp.arange(sk)[None, :]
    keep = jnp.ones((sq, sk), bool)
    if causal:
        keep = keep & (cols <= rows)
    if window is not None:
        keep = keep & (cols >= rows - window[0])
        if not causal:
            keep = keep & (cols <= rows + window[1])
    keep = jnp.broadcast_to(keep[None, None], s.shape)
    if sri is not None:
        n = sri.shape[-1]
        r = rows[None, None]
        sc = jnp.swapaxes(sri, -1, -2)[:, :, :, None, :]  # (B,H,n,1,S_k)

        def col(i):
            return sc[:, :, i]
        if causal and n == 1:
            masked = r >= col(0)
        elif causal and n == 2:
            masked = (r >= col(0)) & (r < col(1))
        elif not causal and n == 2:
            masked = (r >= col(0)) | (r < col(1))
        elif not causal and n == 4:
            masked = ((r >= col(0)) & (r < col(1))) | \
                     ((r >= col(2)) & (r < col(3)))
        else:
            raise ValueError(f"n={n} invalid for causal={causal}")
        keep = keep & ~masked
    s = jnp.where(keep, s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    p = jnp.where(keep, p, 0.0)
    if dropout > 0.0:
        assert dropout_seed is not None, "dropout requires dropout_seed"
        B, H = p.shape[0], p.shape[1]
        bh = (jnp.arange(B)[:, None] * H
              + jnp.arange(H)[None, :])[..., None, None]
        keep_p = dropout_keep_mask(
            jnp.broadcast_to(rows[None, None], p.shape),
            jnp.broadcast_to(cols[None, None], p.shape),
            bh, jnp.asarray(dropout_seed, jnp.int32).reshape(()),
            dropout)
        p = jnp.where(keep_p, p / (1.0 - dropout), 0.0)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o.astype(q.dtype), lse


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------
def _drop_keep(seed_ref, bh, qi, ki, block_q, block_k, dropout):
    """(block_q, block_k) keep-mask + inverse-keep-prob scale for this
    block, from absolute coordinates — fwd and both bwd kernels call
    this with the same (bh, qi, ki) and regenerate the identical mask.
    bh must be read via pl.program_id at kernel top level (it does not
    lower inside a pl.when body under interpret mode)."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = dropout_keep_mask(rows, cols, bh, seed_ref[0], dropout)
    return keep, np.float32(1.0 / (1.0 - dropout))


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, sri_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, window, n_sri,
                block_q, block_k, n_k, sq, sk, dropout):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    srib = sri_ref[0] if sri_ref is not None else None
    compute, keep_mask = _block_keep(qi, ki, block_q, block_k, sq, sk,
                                     causal, window, srib, n_sri)

    @pl.when(compute)
    def body():
        q, k, v = _zero_oob(qi, ki, q_ref[0], k_ref[0], v_ref[0],
                            block_q=block_q, block_k=block_k, sq=sq, sk=sk)
        d = q.shape[-1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        keep = keep_mask()
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - _fit_lanes(m_new, s.shape[-1]))
        p = jnp.where(keep, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        # l (→ lse) accumulates the UNdropped p: dropout applies to the
        # normalized probabilities (reference kernel semantics), which
        # post-normalization equals dropping unnormalized p
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        pd = p
        if dropout > 0.0:
            dkeep, inv = _drop_keep(seed_ref, bh, qi, ki, block_q, block_k,
                                    dropout)
            pd = jnp.where(dkeep, p * inv, 0.0)
        acc_ref[:] = acc_ref[:] * _fit_lanes(alpha, d) + jax.lax.dot_general(
            pd.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new
        l_ref[:] = l_new

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        d = o_ref.shape[-1]
        o_ref[0] = (acc_ref[:] / _fit_lanes(l_safe, d)).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(l_safe)


def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, sri_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_acc, *, scale, causal, window,
                   n_sri, block_q, block_k, n_k, sq, sk, dropout):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    srib = sri_ref[0] if sri_ref is not None else None
    compute, keep_mask = _block_keep(qi, ki, block_q, block_k, sq, sk,
                                     causal, window, srib, n_sri)

    @pl.when(compute)
    def body():
        q, k, v, do = _zero_oob(qi, ki, q_ref[0], k_ref[0], v_ref[0],
                                do_ref[0], block_q=block_q,
                                block_k=block_k, sq=sq, sk=sk)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        keep = keep_mask()
        s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - _fit_lanes(lse_ref[0], s.shape[-1]))
        p = jnp.where(keep, p, 0.0)
        do = do.astype(jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout > 0.0:
            # ds = p ∘ (D∘dp − delta): delta already equals
            # Σ_k p̃ dp (= do·o), so only dp gets the dropout mask
            dkeep, inv = _drop_keep(seed_ref, bh, qi, ki, block_q, block_k,
                                    dropout)
            dp = jnp.where(dkeep, dp * inv, 0.0)
        ds = jnp.where(keep,
                       p * (dp - _fit_lanes(delta_ref[0], dp.shape[-1]))
                       * scale, 0.0)
        dq_acc[:] += jax.lax.dot_general(ds, k.astype(jnp.float32),
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _fin():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, sri_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale,
                    causal, window, n_sri, block_q, block_k, n_q, sq, sk,
                    dropout):
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    srib = sri_ref[0] if sri_ref is not None else None
    compute, keep_mask = _block_keep(qi, ki, block_q, block_k, sq, sk,
                                     causal, window, srib, n_sri)

    @pl.when(compute)
    def body():
        q, k, v, do = _zero_oob(qi, ki, q_ref[0], k_ref[0], v_ref[0],
                                do_ref[0], block_q=block_q,
                                block_k=block_k, sq=sq, sk=sk)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        keep = keep_mask()
        s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - _fit_lanes(lse_ref[0], s.shape[-1]))
        p = jnp.where(keep, p, 0.0)
        do = do.astype(jnp.float32)
        pd = p
        if dropout > 0.0:
            dkeep, inv = _drop_keep(seed_ref, bh, qi, ki, block_q, block_k,
                                    dropout)
            pd = jnp.where(dkeep, p * inv, 0.0)
        dv_acc[:] += jax.lax.dot_general(pd, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout > 0.0:
            dp = jnp.where(dkeep, dp * inv, 0.0)
        ds = jnp.where(keep,
                       p * (dp - _fit_lanes(delta_ref[0], dp.shape[-1]))
                       * scale, 0.0)
        dk_acc[:] += jax.lax.dot_general(ds, q.astype(jnp.float32),
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _fin():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------
def _prep(q, k, v, sri):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)
    if sri is not None:
        # (B,H,S_k,n) -> (bh, n, S_k): the kernel reads (n, block_k)
        # tiles whose LANE dim is the 128-aligned key axis
        n = sri.shape[-1]
        srir = jnp.swapaxes(sri, -1, -2).reshape(bh, n, sk).astype(jnp.int32)
    else:
        srir = None
    return qr, kr, vr, srir, b, h, sq, sk, d, bh


def _mem_spec():
    mem = pltpu.VMEM if _HAS_PLTPU else None
    return (lambda bs, im: pl.BlockSpec(bs, im, memory_space=mem)
            if mem else pl.BlockSpec(bs, im))


def _mk_kernel(fn, have_sri, **kw):
    """Bind statics; when sri is absent, shim a None into the kernel's
    sri_ref slot so one kernel body serves both signatures."""
    if have_sri:
        return functools.partial(fn, **kw)
    return functools.partial(
        lambda seed_, q_, k_, v_, *rest, **kw2:
        fn(seed_, q_, k_, v_, None, *rest, **kw2),
        **kw)


def _seed_spec():
    if _HAS_PLTPU:
        # explicit index map: a memory_space-only BlockSpec gets a
        # pallas-default map whose 0 constant is i64 under x64 — Mosaic
        # rejects the transform func returning i64 (chip-observed:
        # "func.return (i64)" legalization failure, TPU_VALIDATION r5)
        return pl.BlockSpec((1,), lambda *_: (Z,),
                            memory_space=pltpu.SMEM)
    return pl.BlockSpec((1,), lambda *_: (Z,))  # pragma: no cover


def _seed_arr(seed):
    if seed is None:
        return jnp.zeros((1,), jnp.int32)
    return jnp.asarray(seed, jnp.int32).reshape((1,))


def _fwd_pallas(q, k, v, sri, causal, window, scale, block_q, block_k,
                interpret, dropout=0.0, seed=None):
    scale = np.float32(scale)
    qr, kr, vr, srir, b, h, sq, sk, d, bh = _prep(q, k, v, sri)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    n_q = pl.cdiv(sq, block_q)
    n_k = pl.cdiv(sk, block_k)
    n_sri = srir.shape[1] if srir is not None else 0
    spec = _mem_spec()

    in_specs = [
        _seed_spec(),
        spec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, Z)),
        spec((1, block_k, d), lambda bh_, qi, ki: (bh_, ki, Z)),
        spec((1, block_k, d), lambda bh_, qi, ki: (bh_, ki, Z)),
    ]
    args = [_seed_arr(seed), qr, kr, vr]
    if srir is not None:
        in_specs.append(spec((1, n_sri, block_k),
                             lambda bh_, qi, ki: (bh_, Z, ki)))
        args.append(srir)
    kernel = _mk_kernel(_fwd_kernel, srir is not None, scale=scale,
                        causal=causal, window=window, n_sri=n_sri,
                        block_q=block_q, block_k=block_k, n_k=n_k,
                        sq=sq, sk=sk, dropout=dropout)

    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=in_specs,
        out_specs=[
            spec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, Z)),
            spec((1, block_q, LANES), lambda bh_, qi, ki: (bh_, qi, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return o.reshape(b, h, sq, d), lse.reshape(b, h, sq, LANES)


def _bwd_pallas(q, k, v, sri, o, lse, do, causal, window, scale,
                block_q, block_k, interpret, dropout=0.0, seed=None):
    scale = np.float32(scale)
    qr, kr, vr, srir, b, h, sq, sk, d, bh = _prep(q, k, v, sri)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    n_q = pl.cdiv(sq, block_q)
    n_k = pl.cdiv(sk, block_k)
    n_sri = srir.shape[1] if srir is not None else 0
    spec = _mem_spec()

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    dor = do.reshape(bh, sq, d)
    lser = lse.reshape(bh, sq, LANES)
    deltar = jnp.broadcast_to(delta.reshape(bh, sq)[..., None],
                              (bh, sq, LANES))

    def specs(order):
        # order: index-map arg order differs between the two kernels
        qspec = spec((1, block_q, d), order("q"))
        return ([_seed_spec(), qspec,
                 spec((1, block_k, d), order("k")),
                 spec((1, block_k, d), order("k")),
                 ] + ([spec((1, n_sri, block_k), order("sri"))]
                      if srir is not None else []) +
                [spec((1, block_q, d), order("q")),
                 spec((1, block_q, LANES), order("q")),
                 spec((1, block_q, LANES), order("q"))])

    def dq_order(which):
        return {"q": lambda b_, qi, ki: (b_, qi, Z),
                "k": lambda b_, qi, ki: (b_, ki, Z),
                "sri": lambda b_, qi, ki: (b_, Z, ki)}[which]

    def dkv_order(which):
        return {"q": lambda b_, ki, qi: (b_, qi, Z),
                "k": lambda b_, ki, qi: (b_, ki, Z),
                "sri": lambda b_, ki, qi: (b_, Z, ki)}[which]

    base_args = [_seed_arr(seed), qr, kr, vr] + \
        ([srir] if srir is not None else [])

    dq = pl.pallas_call(
        _mk_kernel(_bwd_dq_kernel, srir is not None, scale=scale,
                   causal=causal, window=window, n_sri=n_sri,
                   block_q=block_q, block_k=block_k, n_k=n_k, sq=sq, sk=sk,
                   dropout=dropout),
        grid=(bh, n_q, n_k),
        in_specs=specs(dq_order),
        out_specs=[spec((1, block_q, d), dq_order("q"))],
        out_shape=[jax.ShapeDtypeStruct((bh, sq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)]
        if _HAS_PLTPU else [],
        interpret=interpret,
    )(*base_args, dor, lser, deltar)[0]

    dk, dv = pl.pallas_call(
        _mk_kernel(_bwd_dkv_kernel, srir is not None, scale=scale,
                   causal=causal, window=window, n_sri=n_sri,
                   block_q=block_q, block_k=block_k, n_q=n_q, sq=sq, sk=sk,
                   dropout=dropout),
        grid=(bh, n_k, n_q),
        in_specs=specs(dkv_order),
        out_specs=[
            spec((1, block_k, d), dkv_order("k")),
            spec((1, block_k, d), dkv_order("k")),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ] if _HAS_PLTPU else [],
        interpret=interpret,
    )(*base_args, dor, lser, deltar)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# Public op with custom VJP
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flashmask(q, k, v, sri, seed, causal, window, scale, block_q, block_k,
               interpret, dropout):
    o, _ = _fwd_pallas(q, k, v, sri, causal, window, scale, block_q,
                       block_k, interpret, dropout, seed)
    return o


def _flashmask_fwd(q, k, v, sri, seed, causal, window, scale, block_q,
                   block_k, interpret, dropout):
    o, lse = _fwd_pallas(q, k, v, sri, causal, window, scale, block_q,
                         block_k, interpret, dropout, seed)
    return o, (q, k, v, sri, seed, o, lse)


def _flashmask_bwd(causal, window, scale, block_q, block_k, interpret,
                   dropout, res, do):
    q, k, v, sri, seed, o, lse = res
    dq, dk, dv = _bwd_pallas(q, k, v, sri, o, lse, do, causal, window,
                             scale, block_q, block_k, interpret, dropout,
                             seed)
    dsri = (None if sri is None
            else np.zeros(sri.shape, jax.dtypes.float0))
    dseed = (None if seed is None
             else np.zeros(np.shape(seed), jax.dtypes.float0))
    return dq, dk, dv, dsri, dseed


_flashmask.defvjp(_flashmask_fwd, _flashmask_bwd)


def flashmask_attention_bhsd(q, k, v, startend_row_indices=None, causal=True,
                             window=None, sm_scale=None,
                             block_q=DEFAULT_BLOCK_Q,
                             block_k=DEFAULT_BLOCK_K,
                             use_pallas=None, interpret=None,
                             dropout=0.0, dropout_seed=None):
    """Core entry: q,k,v (B,H,S,D), startend_row_indices (B,H,S_k,n)
    already broadcast to the q heads. O(S·block) memory on the kernel
    path; dense reference off-TPU unless interpret is forced.

    dropout: attention-probability dropout applied IN-KERNEL from a
    deterministic counter-based mask keyed by (dropout_seed, coords) —
    the kernel path stays O(S·block) for every config, dropout
    included (VERDICT r4 item 5). The dense off-TPU reference applies
    the identical mask when given dropout_seed, so the two paths agree
    exactly.
    """
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if window is not None:
        window = (int(window), int(window)) if np.isscalar(window) \
            else (int(window[0]), int(window[1]))
    if dropout > 0.0 and dropout_seed is None:
        raise ValueError("flashmask dropout requires dropout_seed")
    if use_pallas is None:
        use_pallas = _on_tpu() and not pallas_disabled()
    if interpret is None:
        interpret = not _on_tpu()
    if not use_pallas:
        o, _ = flashmask_reference(q, k, v, startend_row_indices, causal,
                                   window, scale, dropout=dropout,
                                   dropout_seed=dropout_seed)
        return o
    return _flashmask(q, k, v, startend_row_indices,
                      _seed_arr(dropout_seed) if dropout > 0.0 else None,
                      causal, window, scale, block_q, block_k, interpret,
                      float(dropout))
