"""Fused-op building blocks (reference: paddle/phi/kernels/fusion/*).

On TPU these are jnp expressions XLA fuses into single HBM passes; they
exist as named ops so models/incubate map 1:1 to the reference surface.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def fused_rms_norm(x, weight, epsilon=1e-6):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(ms + epsilon) * weight.astype(jnp.float32)
    return out.astype(x.dtype)


def fused_swiglu(x, gate_w, up_w, down_w):
    """SwiGLU MLP: down( silu(x@gate) * (x@up) ) — one fused XLA graph."""
    g = jnp.matmul(x, gate_w)
    u = jnp.matmul(x, up_w)
    return jnp.matmul(jax.nn.silu(g) * u, down_w)


def fused_dropout_add(x, residual, p, key, training=True):
    if not training or p == 0.0:
        return x + residual
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), 0.0) + residual
