"""Fused-op building blocks (reference: paddle/phi/kernels/fusion/*).

On TPU these are jnp expressions XLA fuses into single HBM passes; they
exist as named ops so models/incubate map 1:1 to the reference surface.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def fused_rms_norm(x, weight, epsilon=1e-6):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(ms + epsilon) * weight.astype(jnp.float32)
    return out.astype(x.dtype)


def fused_swiglu(x, gate_w, up_w, down_w):
    """SwiGLU MLP: down( silu(x@gate) * (x@up) ) — one fused XLA graph."""
    g = jnp.matmul(x, gate_w)
    u = jnp.matmul(x, up_w)
    return jnp.matmul(jax.nn.silu(g) * u, down_w)


def fused_dropout_add(x, residual, p, key, training=True):
    if not training or p == 0.0:
        return x + residual
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), 0.0) + residual


# ---------------------------------------------------------------------------
# fused linear + cross entropy: never materializes the (N, V) logits.
# Reference: paddle/phi/kernels/fusion fused_linear_param_grad / PaddleNLP's
# parallel_cross_entropy memory optimization. Chunked over vocab with an
# online logsumexp; backward recomputes per-chunk softmax. HBM cost drops
# from O(N·V) to O(N·chunk).
# ---------------------------------------------------------------------------
def _pad_vocab(weight, bias, chunk):
    H, V = weight.shape
    pad = (-V) % chunk
    if pad:
        weight = jnp.pad(weight, ((0, 0), (0, pad)))
        if bias is not None:
            bias = jnp.pad(bias, (0, pad))
    return weight, bias, V + pad


import functools
import numpy as np


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flce(x, weight, bias, labels, chunk):
    loss, _ = _flce_fwd_impl(x, weight, bias, labels, chunk)
    return loss


def _flce_fwd_impl(x, weight, bias, labels, chunk):
    N, H = x.shape
    wp, bp, Vp = _pad_vocab(weight, bias, chunk)
    n_chunks = Vp // chunk
    wc = wp.reshape(H, n_chunks, chunk).transpose(1, 0, 2)   # (C, H, chunk)
    bc = bp.reshape(n_chunks, chunk) if bias is not None else None
    xf = x.astype(jnp.float32)

    V = weight.shape[1]

    def body(carry, ci):
        m, s, lab_logit = carry
        w = wc[ci].astype(jnp.float32)
        logits = xf @ w                                     # (N, chunk)
        if bc is not None:
            logits = logits + bc[ci]
        base = ci * chunk
        # padded vocab columns must not feed the logsumexp
        logits = jnp.where(base + jnp.arange(chunk)[None, :] < V, logits,
                           -1e30)
        # pick out this chunk's label logits
        in_chunk = (labels >= base) & (labels < base + chunk)
        local = jnp.clip(labels - base, 0, chunk - 1)
        picked = jnp.take_along_axis(logits, local[:, None], axis=1)[:, 0]
        lab_logit = jnp.where(in_chunk, picked, lab_logit)
        m_new = jnp.maximum(m, jnp.max(logits, axis=1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        return (m_new, s, lab_logit), None

    init = (jnp.full((N,), -1e30, jnp.float32),
            jnp.zeros((N,), jnp.float32),
            jnp.zeros((N,), jnp.float32))
    (m, s, lab_logit), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    lse = m + jnp.log(s)
    return lse - lab_logit, lse


def _flce_fwd(x, weight, bias, labels, chunk):
    loss, lse = _flce_fwd_impl(x, weight, bias, labels, chunk)
    return loss, (x, weight, bias, labels, lse)


def _flce_bwd(chunk, res, g):
    x, weight, bias, labels, lse = res
    N, H = x.shape
    wp, bp, Vp = _pad_vocab(weight, bias, chunk)
    n_chunks = Vp // chunk
    wc = wp.reshape(H, n_chunks, chunk).transpose(1, 0, 2)
    bc = bp.reshape(n_chunks, chunk) if bias is not None else None
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)

    V = weight.shape[1]

    def body(dx, ci):
        w = wc[ci].astype(jnp.float32)
        logits = xf @ w
        if bc is not None:
            logits = logits + bc[ci]
        base = ci * chunk
        logits = jnp.where(base + jnp.arange(chunk)[None, :] < V, logits,
                           -1e30)
        p = jnp.exp(logits - lse[:, None])                  # softmax chunk
        local = labels - base
        onehot = (jnp.arange(chunk)[None, :] == local[:, None])
        d_logits = (p - onehot) * gf[:, None]               # (N, chunk)
        dx = dx + d_logits @ w.T
        dw_c = xf.T @ d_logits                              # (H, chunk)
        db_c = jnp.sum(d_logits, axis=0) if bc is not None else None
        return dx, (dw_c, db_c)

    dx0 = jnp.zeros((N, H), jnp.float32)
    dx, (dw_chunks, db_chunks) = jax.lax.scan(body, dx0, jnp.arange(n_chunks))
    V = weight.shape[1]
    dw = dw_chunks.transpose(1, 0, 2).reshape(H, Vp)[:, :V]
    db = db_chunks.reshape(Vp)[:V] if bias is not None else None
    dlabels = np.zeros(labels.shape, dtype=jax.dtypes.float0)
    return (dx.astype(x.dtype), dw.astype(weight.dtype),
            db.astype(bias.dtype) if bias is not None else None, dlabels)


_flce.defvjp(_flce_fwd, _flce_bwd)


def fused_linear_cross_entropy(x, weight, labels, bias=None, chunk_size=8192,
                               reduction="mean", ignore_index=-100):
    """CE(x @ weight + bias, labels) without materializing the logits.

    x: (N, H) hidden states; weight: (H, V); labels: (N,) int.
    """
    labels = labels.astype(jnp.int32)
    chunk = min(chunk_size, weight.shape[1])
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    loss = _flce(x, weight, bias, safe_labels, chunk)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss
