"""Paged attention for TPU decode (serving path).

Reference parity: the reference serves LLMs through paged/block KV caches
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention — block_tables,
per-seq lengths). TPU-native redesign:

  * KV lives in a page pool `(kv_heads, num_pages, page_size, head_dim)`.
  * Each sequence owns a row of `page_table` (page indices) + a length.
  * The decode kernel runs grid `(batch, kv_heads, pages_per_seq)`; the
    page table and lengths ride scalar-prefetch (SMEM) so the BlockSpec
    index_map DMAs exactly the page each step needs — no gather of the
    whole cache. Online softmax (m/l lane-replicated scratch) accumulates
    across the page grid dimension; fully-masked pages are skipped with
    @pl.when (ragged batches don't pay for their padding).
  * GQA: q is viewed (batch, kv_heads, group, head_dim); group is padded
    to the sublane minimum (8) in the wrapper.

Off-TPU the XLA reference path (gather pages → dense softmax) is used;
the kernel also runs under pallas interpret mode for tests.
"""
from __future__ import annotations

import functools

import jax
import numpy as np
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _fit_lanes

NEG_INF = np.float32(-1e30)  # f32: Mosaic rejects f64 consts under x64
Z = np.int32(0)           # i32 index-map consts (x64 would make them i64)
LANES = 128
MIN_GROUP = 8  # TPU sublane minimum for the q-rows dim


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# int8 cache quantization (reference parity: the cachekv-quant decode in
# paddle/phi/kernels/fusion/gpu/block_attn.h — int8 KV pages with scales,
# dequantized inside the attention kernel). Per-token-per-head absmax:
# one fp32 scale per stored (head, token) vector.
# ---------------------------------------------------------------------------
def quantize_kv(x, axis=-1):
    """x: (..., D) → (int8 values, fp32 scale with D→1 kept).

    scale = absmax/127 (floored to avoid div-by-zero on all-zero
    vectors, e.g. untouched pool pages)."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=axis, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale):
    return q.astype(jnp.float32) * scale


# ---------------------------------------------------------------------------
# Reference (XLA) implementation
# ---------------------------------------------------------------------------
def _gather_pages(k_pages, v_pages, page_table, k_scale, v_scale):
    """(B, KVH, pages_per_seq*page, D) contiguous dequantized views of
    each sequence's pages — shared by both XLA reference paths."""
    b = page_table.shape[0]
    kvh, _, _, d = k_pages.shape
    k = jnp.swapaxes(k_pages[:, page_table], 0, 1).reshape(b, kvh, -1, d)
    v = jnp.swapaxes(v_pages[:, page_table], 0, 1).reshape(b, kvh, -1, d)
    if k_scale is not None:  # dequantize the gathered slices only
        ks = jnp.swapaxes(k_scale[:, page_table], 0, 1).reshape(b, kvh, -1, 1)
        vs = jnp.swapaxes(v_scale[:, page_table], 0, 1).reshape(b, kvh, -1, 1)
        k = dequantize_kv(k, ks)
        v = dequantize_kv(v, vs)
    return k, v


def paged_attention_reference(q, k_pages, v_pages, page_table, lengths,
                              sm_scale=None, k_scale=None, v_scale=None):
    """q: (B, QH, D); pages: (KVH, P, page, D); page_table: (B, pages_per_seq);
    lengths: (B,). k_scale/v_scale: (KVH, P, page, 1) fp32 when the
    pages are int8-quantized. Returns (B, QH, D)."""
    b, qh, d = q.shape
    kvh = k_pages.shape[0]
    group = qh // kvh
    scale = sm_scale if sm_scale is not None else d ** -0.5
    k, v = _gather_pages(k_pages, v_pages, page_table, k_scale, v_scale)
    qg = q.reshape(b, kvh, group, d).astype(jnp.float32)
    s = jnp.einsum("bhgd,bhkd->bhgk", qg, k.astype(jnp.float32)) * scale
    mask = jnp.arange(s.shape[-1])[None, None, None] < lengths[:, None, None,
                                                               None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgk,bhkd->bhgd", p, v.astype(jnp.float32))
    return o.reshape(b, qh, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------
def _decode_kernel(ptab_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, page_size, n_pages,
                   ks_ref=None, vs_ref=None):
    """ks_ref/vs_ref: per-token fp32 scale blocks (1, 1, page, 1) when
    the K/V pages are int8 — dequantized HERE, so the int8 pool is what
    rides HBM→VMEM (the whole point of cache quantization)."""
    del ptab_ref  # consumed by the index maps
    bi = pl.program_id(0)
    pi = pl.program_id(2)

    @pl.when(pi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    seq_len = len_ref[bi]

    @pl.when(pi * page_size < seq_len)  # skip fully-masked pages
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)   # (group, d)
        k = k_ref[0, 0].astype(jnp.float32)   # (page, d)
        v = v_ref[0, 0].astype(jnp.float32)
        if ks_ref is not None:
            k = k * ks_ref[0, 0]              # (page, 1) broadcast over d
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(cols < seq_len, s, NEG_INF)
        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - _fit_lanes(m_new, s.shape[-1]))
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * _fit_lanes(alpha, acc_ref.shape[-1]) + \
            jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(pi == n_pages - 1)
    def _fin():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] /
                       _fit_lanes(l_safe, o_ref.shape[-1])).astype(o_ref.dtype)


def _quant_kernel(ptab_ref, len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                  o_ref, acc_ref, m_ref, l_ref, **kw):
    """Positional adapter: pallas passes the two scale inputs between
    v and the output ref."""
    _decode_kernel(ptab_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, ks_ref=ks_ref, vs_ref=vs_ref,
                   **kw)


def _decode_pallas(q4, k_pages, v_pages, page_table, lengths, scale,
                   interpret, k_scale=None, v_scale=None):
    b, kvh, group, d = q4.shape
    _, _, page_size, _ = k_pages.shape
    n_pages = page_table.shape[1]
    quant = k_scale is not None

    # index maps receive grid indices first, then scalar-prefetch refs
    page_spec = pl.BlockSpec((1, 1, page_size, d),
                             lambda bi, hi, pi, ptab, lens:
                             (hi, ptab[bi, pi], Z, Z))
    in_specs = [
        pl.BlockSpec((1, 1, group, d),
                     lambda bi, hi, pi, ptab, lens: (bi, hi, Z, Z)),
        page_spec,
        page_spec,
    ]
    operands = [page_table, lengths, q4, k_pages, v_pages]
    if quant:
        scale_spec = pl.BlockSpec((1, 1, page_size, 1),
                                  lambda bi, hi, pi, ptab, lens:
                                  (hi, ptab[bi, pi], Z, Z))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda bi, hi, pi, ptab, lens: (bi, hi, Z, Z)),
        scratch_shapes=[
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, LANES), jnp.float32),
            pltpu.VMEM((group, LANES), jnp.float32),
        ],
    )
    kernel = functools.partial(_quant_kernel if quant else _decode_kernel,
                               scale=np.float32(scale),
                               page_size=page_size, n_pages=n_pages)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, group, d), q4.dtype),
        interpret=interpret,
    )(*operands)


def paged_attention(q, k_pages, v_pages, page_table, lengths, sm_scale=None,
                    use_pallas=None, interpret=None, k_scale=None,
                    v_scale=None):
    """Single-token decode attention over a paged KV cache.

    q: (B, QH, D); k_pages/v_pages: (KVH, num_pages, page_size, D);
    page_table: (B, pages_per_seq) int32; lengths: (B,) int32.

    int8 cache: pass int8 pages plus k_scale/v_scale fp32 per-token
    scales (KVH, num_pages, page_size, 1) — see quantize_kv. The pages
    are dequantized inside the kernel (reference parity: cachekv-quant
    in phi/kernels/fusion/gpu/block_attn.h), halving/quartering the
    HBM traffic and pool footprint vs bf16/fp32.
    """
    b, qh, d = q.shape
    kvh = k_pages.shape[0]
    group = qh // kvh
    scale = sm_scale if sm_scale is not None else d ** -0.5
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if use_pallas is None:
        use_pallas = _on_tpu()
    if interpret is None:
        interpret = False
    if not use_pallas and not interpret:
        return paged_attention_reference(q, k_pages, v_pages, page_table,
                                         lengths, scale, k_scale, v_scale)
    q4 = q.reshape(b, kvh, group, d)
    # q-rows block dim must be a multiple of the sublane tile (8)
    pad = (-group) % MIN_GROUP
    if pad:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, pad), (0, 0)))
    o = _decode_pallas(q4, k_pages, v_pages,
                       page_table.astype(jnp.int32),
                       lengths.astype(jnp.int32), scale, interpret,
                       k_scale=k_scale, v_scale=v_scale)
    if pad:
        o = o[:, :, :group]
    return o.reshape(b, qh, d)


# ---------------------------------------------------------------------------
# Multi-query (verify-chunk) paged attention — speculative decoding /
# chunked prefill: G chunk tokens per sequence attend against the paged
# cache in one kernel, token g seeing keys 0 .. base+g (its own position
# included; the chunk's K/V were scattered into the pages beforehand).
# Same page-streaming structure as the decode kernel, with a per-ROW
# column limit instead of a single per-sequence one.
# ---------------------------------------------------------------------------
def paged_verify_reference(q, k_pages, v_pages, page_table, base_lengths,
                           sm_scale=None, k_scale=None, v_scale=None):
    """q: (B, QH, G, D); pages as in paged_attention; base_lengths: (B,)
    cache length BEFORE the chunk. Returns (B, QH, G, D)."""
    b, qh, g, d = q.shape
    kvh = k_pages.shape[0]
    group = qh // kvh
    scale = sm_scale if sm_scale is not None else d ** -0.5
    k, v = _gather_pages(k_pages, v_pages, page_table, k_scale, v_scale)
    qg = q.reshape(b, kvh, group, g, d).astype(jnp.float32)
    s = jnp.einsum("bhxgd,bhkd->bhxgk", qg, k.astype(jnp.float32)) * scale
    cols = jnp.arange(s.shape[-1])[None, None, None, None]
    limit = (base_lengths[:, None, None, None, None]
             + jnp.arange(g)[None, None, None, :, None] + 1)
    s = jnp.where(cols < limit, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhxgk,bhkd->bhxgd", p, v.astype(jnp.float32))
    return o.reshape(b, qh, g, d).astype(q.dtype)


def _verify_kernel(ptab_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, page_size, n_pages,
                   n_tok, ks_ref=None, vs_ref=None):
    """q rows are (group_pad * n_tok): r = gg * n_tok + g — token
    g = r % n_tok sees columns < base + g + 1."""
    del ptab_ref
    bi = pl.program_id(0)
    pi = pl.program_id(2)

    @pl.when(pi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    base = len_ref[bi]

    @pl.when(pi * page_size < base + n_tok)  # skip fully-masked pages
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)   # (group_pad*n_tok, d)
        k = k_ref[0, 0].astype(jnp.float32)   # (page, d)
        v = v_ref[0, 0].astype(jnp.float32)
        if ks_ref is not None:
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        # np.int32 divisor, NOT the bare python int: `% n_tok` binds the
        # int as a strong i64 const under x64, and Mosaic's int64->int32
        # convert recurses forever (chip-observed RecursionError,
        # TPU_VALIDATION r5).
        g_row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) \
            % np.int32(n_tok)
        s = jnp.where(cols < base + g_row + 1, s, NEG_INF)
        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - _fit_lanes(m_new, s.shape[-1]))
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * _fit_lanes(alpha, acc_ref.shape[-1]) + \
            jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(pi == n_pages - 1)
    def _fin():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] /
                       _fit_lanes(l_safe, o_ref.shape[-1])).astype(o_ref.dtype)


def _verify_quant_kernel(ptab_ref, len_ref, q_ref, k_ref, v_ref, ks_ref,
                         vs_ref, o_ref, acc_ref, m_ref, l_ref, **kw):
    _verify_kernel(ptab_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, ks_ref=ks_ref, vs_ref=vs_ref,
                   **kw)


def paged_verify_attention(q, k_pages, v_pages, page_table, base_lengths,
                           sm_scale=None, use_pallas=None, interpret=None,
                           k_scale=None, v_scale=None):
    """Verify-chunk attention over a paged KV cache.

    q: (B, QH, G, D); pages/page_table as paged_attention;
    base_lengths: (B,) cache length BEFORE the chunk (token g of the
    chunk sits at absolute position base+g and may attend through
    itself). int8 pages take k_scale/v_scale exactly like the decode
    kernel. Returns (B, QH, G, D).

    This is the pallas replacement for the gather-based dense verify
    block: pages stream HBM→VMEM via scalar-prefetch index maps (no
    materialized contiguous copy), masked pages are skipped, and every
    q row of the (group × G) block shares the one page read.
    """
    b, qh, g, d = q.shape
    kvh = k_pages.shape[0]
    group = qh // kvh
    scale = sm_scale if sm_scale is not None else d ** -0.5
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if use_pallas is None:
        use_pallas = _on_tpu()
    if interpret is None:
        interpret = False
    if not use_pallas and not interpret:
        return paged_verify_reference(q, k_pages, v_pages, page_table,
                                      base_lengths, scale, k_scale, v_scale)
    # rows: r = gg * G + g (head-major) so r % G recovers the token.
    # Pad whole head-groups until (group_pad * G) hits the sublane tile
    # (8): the smallest e with (group+e)*G % 8 == 0 is e = (-group) mod
    # (8 / gcd(G, 8)) — padding a partial group would break the r % G
    # token mapping, and an unaligned row block is a Mosaic rejection.
    import math as _math
    r_mod = MIN_GROUP // _math.gcd(g, MIN_GROUP)
    extra_groups = (-group) % r_mod
    group_pad = group + extra_groups
    q5 = q.reshape(b, kvh, group, g, d)
    if extra_groups:
        q5 = jnp.pad(q5, ((0, 0), (0, 0), (0, extra_groups), (0, 0), (0, 0)))
    q4 = q5.reshape(b, kvh, group_pad * g, d)

    page_size = k_pages.shape[2]
    n_pages = page_table.shape[1]
    quant = k_scale is not None
    page_spec = pl.BlockSpec((1, 1, page_size, d),
                             lambda bi, hi, pi, ptab, lens:
                             (hi, ptab[bi, pi], Z, Z))
    in_specs = [
        pl.BlockSpec((1, 1, group_pad * g, d),
                     lambda bi, hi, pi, ptab, lens: (bi, hi, Z, Z)),
        page_spec,
        page_spec,
    ]
    operands = [page_table.astype(jnp.int32),
                base_lengths.astype(jnp.int32), q4, k_pages, v_pages]
    if quant:
        scale_spec = pl.BlockSpec((1, 1, page_size, 1),
                                  lambda bi, hi, pi, ptab, lens:
                                  (hi, ptab[bi, pi], Z, Z))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group_pad * g, d),
                               lambda bi, hi, pi, ptab, lens: (bi, hi, Z, Z)),
        scratch_shapes=[
            pltpu.VMEM((group_pad * g, d), jnp.float32),
            pltpu.VMEM((group_pad * g, LANES), jnp.float32),
            pltpu.VMEM((group_pad * g, LANES), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _verify_quant_kernel if quant else _verify_kernel,
        scale=np.float32(scale), page_size=page_size, n_pages=n_pages,
        n_tok=g)
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, group_pad * g, d), q.dtype),
        interpret=interpret,
    )(*operands)
    o = o.reshape(b, kvh, group_pad, g, d)[:, :, :group]
    return o.reshape(b, qh, g, d)


# ---------------------------------------------------------------------------
# Page pool / cache manager (host-side bookkeeping, device-side pool)
# ---------------------------------------------------------------------------
class PagedKVCache:
    """Per-layer paged KV pool with host-side free-list allocation.

    The pool tensors are device arrays updated functionally (scatter into
    pages); the page table / lengths / free list are host state — the
    serving loop mutates them between jitted decode steps, mirroring how
    the reference's BlockManager hands block_tables to the kernel.
    """

    def __init__(self, num_layers, kv_heads, head_dim, num_pages, page_size,
                 max_seqs, pages_per_seq, dtype=jnp.bfloat16):
        shape = (num_layers, kv_heads, num_pages, page_size, head_dim)
        # dtype "int8": quantized pool + per-token fp32 scales — 2x
        # (vs bf16) / 4x (vs fp32) the servable tokens per pool byte
        self.quantized = dtype in ("int8", jnp.int8)
        if self.quantized:
            self.k = jnp.zeros(shape, jnp.int8)
            self.v = jnp.zeros(shape, jnp.int8)
            sshape = shape[:-1] + (1,)
            self.k_scale = jnp.zeros(sshape, jnp.float32)
            self.v_scale = jnp.zeros(sshape, jnp.float32)
        else:
            self.k = jnp.zeros(shape, dtype)
            self.v = jnp.zeros(shape, dtype)
            self.k_scale = self.v_scale = None
        self.page_size = page_size
        self.page_table = jnp.zeros((max_seqs, pages_per_seq), jnp.int32)
        self.lengths = jnp.zeros((max_seqs,), jnp.int32)
        self._free = list(range(num_pages - 1, -1, -1))
        self._seq_pages = {}  # seq slot -> [page ids]

    def alloc_seq(self, slot, prompt_len):
        n = -(-max(prompt_len, 1) // self.page_size)
        if len(self._free) < n:
            raise RuntimeError("PagedKVCache: out of pages")
        pages = [self._free.pop() for _ in range(n)]
        self._seq_pages[slot] = pages
        tbl = self.page_table.at[slot, :n].set(jnp.asarray(pages, jnp.int32))
        self.page_table = tbl
        self.lengths = self.lengths.at[slot].set(prompt_len)
        return pages

    def extend_seq(self, slot):
        """Called before writing one more token; grabs a page on boundary."""
        cur = int(self.lengths[slot])
        if cur % self.page_size == 0 and cur > 0:
            if not self._free:
                raise RuntimeError("PagedKVCache: out of pages")
            pg = self._free.pop()
            idx = len(self._seq_pages[slot])
            self._seq_pages[slot].append(pg)
            self.page_table = self.page_table.at[slot, idx].set(pg)
        self.lengths = self.lengths.at[slot].add(1)

    def free_seq(self, slot):
        self._free.extend(reversed(self._seq_pages.pop(slot, [])))
        self.lengths = self.lengths.at[slot].set(0)

    def write_token(self, layer, slot, k_tok, v_tok):
        """k_tok/v_tok: (KVH, D) for the token at position lengths[slot]-1."""
        pos = int(self.lengths[slot]) - 1
        pg = self._seq_pages[slot][pos // self.page_size]
        off = pos % self.page_size
        if self.quantized:
            kq, ks = quantize_kv(k_tok)
            vq, vs = quantize_kv(v_tok)
            self.k = self.k.at[layer, :, pg, off].set(kq)
            self.v = self.v.at[layer, :, pg, off].set(vq)
            self.k_scale = self.k_scale.at[layer, :, pg, off].set(ks)
            self.v_scale = self.v_scale.at[layer, :, pg, off].set(vs)
            return
        self.k = self.k.at[layer, :, pg, off].set(k_tok.astype(self.k.dtype))
        self.v = self.v.at[layer, :, pg, off].set(v_tok.astype(self.v.dtype))
