"""Rotary position embedding (reference: paddle/phi/kernels/fusion/gpu/
fused_rope_* and PaddleNLP's RotaryEmbedding).

Pure-XLA implementation: on TPU the rotate-half + multiply fuses into
the surrounding attention matmuls; a pallas kernel buys nothing here.
"""
from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(seq_len, head_dim, base=10000.0, dtype=jnp.float32,
                 position_ids=None):
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                               / head_dim))
    if position_ids is None:
        t = jnp.arange(seq_len, dtype=jnp.float32)
    else:
        t = position_ids.astype(jnp.float32)
    freqs = jnp.einsum("...s,d->...sd", t, inv_freq)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_emb(q, k, cos, sin):
    """q,k: (..., S, H, D) or (..., H, S, D) with cos/sin (..., S, D):
    caller aligns; S must broadcast along the -2 of cos/sin insertion."""
    # cos/sin: (S, D) → broadcast over batch and heads at axis -2
    while cos.ndim < q.ndim:
        cos = cos[None]
        sin = sin[None]
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    q_out = qf * cos + _rotate_half(qf) * sin
    k_out = kf * cos + _rotate_half(kf) * sin
    return q_out.astype(q.dtype), k_out.astype(k.dtype)
