"""Varlen (unpadded / packed) flash attention — TPU pallas kernel.

Reference parity: python/paddle/nn/functional/flash_attention.py:756
(`flash_attn_unpadded`: packed (total, H, D) tensors + cu_seqlens prefix
sums — the serving-prefill workhorse for ragged batches).

TPU-native redesign: instead of the CUDA kernel's per-sequence pointer
arithmetic, sequences are packed along one token axis and masked by
*segment ids* — the layout XLA/Mosaic likes (static shapes, no gathers):

  * seg ids are derived from cu_seqlens (prefix sums) host/trace side;
  * q seg ids ride lane-replicated  (T_q, LANES)  blocks,
    k seg ids ride sublane-replicated (8, T_k)     blocks — both satisfy
    the TPU (8, 128) min-tile rule (same trick as the dense kernel's lse);
  * a position pair is attendable iff seg_q == seg_k (and, for causal,
    k_pos <= q_pos — packed positions are monotone inside a segment so
    global-position causality is exact within a segment);
  * padding tokens (beyond cu_seqlens[-1]) get sentinel segments that
    never match (q-pad = -1, k-pad = -2), so they attend nothing and
    contribute nothing; fully-masked rows resolve to output 0 via the
    safe-l trick and are masked out of the backward by `valid`.

The backward follows the dense kernel's two-pass structure (dq pass over
q blocks, dk/dv pass over k blocks) with the same segment masks.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from .flash_attention import (DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, LANES,
                              NEG_INF, Z, _fit_lanes, _on_tpu)

SUBLANES = 8


# ---------------------------------------------------------------------------
# Reference (pure XLA) implementation over packed layout
# ---------------------------------------------------------------------------
def rev_pos(seg):
    """Per-token distance from its segment's end (monotone seg ids):
    r[i] = (index one past the segment end) - i. Bottom-right-aligned
    causality (flash-attention semantics for unequal q/k lengths) is then
    simply r_k >= r_q — independent of where the segment sits in the pack.

    Negative ids mark padding (always trailing); they are remapped to a
    large value before the binary search so the array stays monotone —
    searchsorted on a non-monotone array would corrupt the segment ends
    of REAL tokens, not just the pads."""
    seg = seg.astype(jnp.int32)
    n = seg.shape[0]
    mono = jnp.where(seg < 0, jnp.int32(2**31 - 1), seg)
    ends = jnp.searchsorted(mono, mono, side="right").astype(jnp.int32)
    return ends - jnp.arange(n, dtype=jnp.int32)


def varlen_reference(q, k, v, seg_q, seg_k, causal, scale):
    """q: (H, Tq, D), k/v: (H, Tk, D), seg ids (Tq,)/(Tk,) int32.
    Returns (out (H, Tq, D), lse (H, Tq))."""
    s = jnp.einsum("hqd,hkd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    valid = seg_q[:, None] == seg_k[None, :]
    if causal:
        rq, rk = rev_pos(seg_q), rev_pos(seg_k)
        valid = valid & (rk[None, :] >= rq[:, None])
    s = jnp.where(valid[None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    e = jnp.where(valid[None], e, 0.0)
    l = jnp.sum(e, axis=-1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("hqk,hkd->hqd", e / l_safe, v.astype(jnp.float32))
    lse = (m + jnp.log(l_safe))[..., 0]
    return o.astype(q.dtype), lse


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------
def _vfwd_kernel(q_ref, k_ref, v_ref, sq_ref, sk_ref, pq_ref, pk_ref,
                 o_ref, lse_ref, acc_ref, m_ref, l_ref, *, scale, causal,
                 same_offsets, block_q, block_k, n_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def body():
        q = q_ref[0]                      # (block_q, d)
        k = k_ref[0]                      # (block_k, d)
        v = v_ref[0]
        d = q.shape[-1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        # q seg (block_q, LANES) tiled out to block_k lanes; k seg compared
        # as a (1, block_k) row — only a sublane broadcast, which Mosaic
        # handles (mirrors jax's tpu flash kernel segment-mask layout)
        valid = _fit_lanes(sq_ref[:], s.shape[-1]) == sk_ref[:1, :]
        if causal:
            # bottom-right alignment: k attendable iff its distance from
            # segment end >= q's (equal-length segments reduce to the
            # standard row>=col mask)
            valid = valid & (pk_ref[:1, :] >= _fit_lanes(pq_ref[:],
                                                         s.shape[-1]))
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - _fit_lanes(m_new, s.shape[-1]))
        p = jnp.where(valid, p, 0.0)      # rows with no valid col stay 0
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * _fit_lanes(alpha, d) + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal and same_offsets:
        # diagonal skip is only sound when q and k tokens share offsets
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        d = o_ref.shape[-1]
        o_ref[0] = (acc_ref[:] / _fit_lanes(l_safe, d)).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(l_safe)


def _pad_to(x, n, axis, value=0):
    pad = n - x.shape[axis]
    if pad <= 0:
        return x
    cfg = [(0, 0)] * x.ndim
    cfg[axis] = (0, pad)
    return jnp.pad(x, cfg, constant_values=value)


def _vfwd_pallas(q, k, v, seg_q, seg_k, pos_q, pos_k, causal, same_offsets,
                 scale, block_q, block_k, interpret):
    """q: (H, Tq, D) padded to block multiples; seg/pos (Tq,)/(Tk,)."""
    scale = np.float32(scale)
    h, tq, d = q.shape
    tk = k.shape[1]
    n_q = tq // block_q
    n_k = tk // block_k
    sq2 = jnp.broadcast_to(seg_q[:, None], (tq, LANES))
    sk2 = jnp.broadcast_to(seg_k[None, :], (SUBLANES, tk))
    pq2 = jnp.broadcast_to(pos_q[:, None], (tq, LANES))
    pk2 = jnp.broadcast_to(pos_k[None, :], (SUBLANES, tk))

    mem = pltpu.VMEM if _HAS_PLTPU else None
    spec = (lambda bs, im: pl.BlockSpec(bs, im, memory_space=mem)
            if mem else pl.BlockSpec(bs, im))
    kernel = functools.partial(_vfwd_kernel, scale=scale, causal=causal,
                               same_offsets=same_offsets,
                               block_q=block_q, block_k=block_k, n_k=n_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=(h, n_q, n_k),
        in_specs=[
            spec((1, block_q, d), lambda hi, qi, ki: (hi, qi, Z)),
            spec((1, block_k, d), lambda hi, qi, ki: (hi, ki, Z)),
            spec((1, block_k, d), lambda hi, qi, ki: (hi, ki, Z)),
            spec((block_q, LANES), lambda hi, qi, ki: (qi, Z)),
            spec((SUBLANES, block_k), lambda hi, qi, ki: (Z, ki)),
            spec((block_q, LANES), lambda hi, qi, ki: (qi, Z)),
            spec((SUBLANES, block_k), lambda hi, qi, ki: (Z, ki)),
        ],
        out_specs=[
            spec((1, block_q, d), lambda hi, qi, ki: (hi, qi, Z)),
            spec((1, block_q, LANES), lambda hi, qi, ki: (hi, qi, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((h, tq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, sq2, sk2, pq2, pk2)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------
def _vbwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    sq_ref, sk_ref, pq_ref, pk_ref, dq_ref, dq_acc, *,
                    scale, causal, same_offsets, block_q, block_k, n_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        valid = _fit_lanes(sq_ref[:], s.shape[-1]) == sk_ref[:1, :]
        if causal:
            valid = valid & (pk_ref[:1, :] >= _fit_lanes(pq_ref[:],
                                                         s.shape[-1]))
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - _fit_lanes(lse_ref[0], s.shape[-1]))
        p = jnp.where(valid, p, 0.0)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = jnp.where(valid,
                       p * (dp - _fit_lanes(delta_ref[0], dp.shape[-1]))
                       * scale, 0.0)
        dq_acc[:] += jax.lax.dot_general(ds, k.astype(jnp.float32),
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal and same_offsets:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(ki == n_k - 1)
    def _fin():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _vbwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     sq_ref, sk_ref, pq_ref, pk_ref, dk_ref, dv_ref,
                     dk_acc, dv_acc, *, scale, causal, same_offsets,
                     block_q, block_k, n_q):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        valid = _fit_lanes(sq_ref[:], s.shape[-1]) == sk_ref[:1, :]
        if causal:
            valid = valid & (pk_ref[:1, :] >= _fit_lanes(pq_ref[:],
                                                         s.shape[-1]))
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - _fit_lanes(lse_ref[0], s.shape[-1]))
        p = jnp.where(valid, p, 0.0)
        do = do_ref[0].astype(jnp.float32)
        dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = jnp.where(valid,
                       p * (dp - _fit_lanes(delta_ref[0], dp.shape[-1]))
                       * scale, 0.0)
        dk_acc[:] += jax.lax.dot_general(ds, q.astype(jnp.float32),
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal and same_offsets:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(qi == n_q - 1)
    def _fin():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _vbwd_pallas(q, k, v, o, lse, do, seg_q, seg_k, pos_q, pos_k, causal,
                 same_offsets, scale, block_q, block_k, interpret):
    scale = np.float32(scale)
    h, tq, d = q.shape
    tk = k.shape[1]
    n_q = tq // block_q
    n_k = tk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lser = jnp.broadcast_to(lse[..., None], (h, tq, LANES))
    deltar = jnp.broadcast_to(delta[..., None], (h, tq, LANES))
    sq2 = jnp.broadcast_to(seg_q[:, None], (tq, LANES))
    sk2 = jnp.broadcast_to(seg_k[None, :], (SUBLANES, tk))
    pq2 = jnp.broadcast_to(pos_q[:, None], (tq, LANES))
    pk2 = jnp.broadcast_to(pos_k[None, :], (SUBLANES, tk))

    mem = pltpu.VMEM if _HAS_PLTPU else None
    spec = (lambda bs, im: pl.BlockSpec(bs, im, memory_space=mem)
            if mem else pl.BlockSpec(bs, im))

    dq = pl.pallas_call(
        functools.partial(_vbwd_dq_kernel, scale=scale, causal=causal,
                          same_offsets=same_offsets,
                          block_q=block_q, block_k=block_k, n_k=n_k),
        grid=(h, n_q, n_k),
        in_specs=[
            spec((1, block_q, d), lambda hi, qi, ki: (hi, qi, Z)),
            spec((1, block_k, d), lambda hi, qi, ki: (hi, ki, Z)),
            spec((1, block_k, d), lambda hi, qi, ki: (hi, ki, Z)),
            spec((1, block_q, d), lambda hi, qi, ki: (hi, qi, Z)),
            spec((1, block_q, LANES), lambda hi, qi, ki: (hi, qi, Z)),
            spec((1, block_q, LANES), lambda hi, qi, ki: (hi, qi, Z)),
            spec((block_q, LANES), lambda hi, qi, ki: (qi, Z)),
            spec((SUBLANES, block_k), lambda hi, qi, ki: (Z, ki)),
            spec((block_q, LANES), lambda hi, qi, ki: (qi, Z)),
            spec((SUBLANES, block_k), lambda hi, qi, ki: (Z, ki)),
        ],
        out_specs=[spec((1, block_q, d), lambda hi, qi, ki: (hi, qi, Z))],
        out_shape=[jax.ShapeDtypeStruct((h, tq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)]
        if _HAS_PLTPU else [],
        interpret=interpret,
    )(q, k, v, do, lser, deltar, sq2, sk2, pq2, pk2)[0]

    dk, dv = pl.pallas_call(
        functools.partial(_vbwd_dkv_kernel, scale=scale, causal=causal,
                          same_offsets=same_offsets,
                          block_q=block_q, block_k=block_k, n_q=n_q),
        grid=(h, n_k, n_q),
        in_specs=[
            spec((1, block_q, d), lambda hi, ki, qi: (hi, qi, Z)),
            spec((1, block_k, d), lambda hi, ki, qi: (hi, ki, Z)),
            spec((1, block_k, d), lambda hi, ki, qi: (hi, ki, Z)),
            spec((1, block_q, d), lambda hi, ki, qi: (hi, qi, Z)),
            spec((1, block_q, LANES), lambda hi, ki, qi: (hi, qi, Z)),
            spec((1, block_q, LANES), lambda hi, ki, qi: (hi, qi, Z)),
            spec((block_q, LANES), lambda hi, ki, qi: (qi, Z)),
            spec((SUBLANES, block_k), lambda hi, ki, qi: (Z, ki)),
            spec((block_q, LANES), lambda hi, ki, qi: (qi, Z)),
            spec((SUBLANES, block_k), lambda hi, ki, qi: (Z, ki)),
        ],
        out_specs=[
            spec((1, block_k, d), lambda hi, ki, qi: (hi, ki, Z)),
            spec((1, block_k, d), lambda hi, ki, qi: (hi, ki, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((h, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ] if _HAS_PLTPU else [],
        interpret=interpret,
    )(q, k, v, do, lser, deltar, sq2, sk2, pq2, pk2)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp op over padded packed layout
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12))
def _varlen_mha(q, k, v, seg_q, seg_k, pos_q, pos_k, causal, same_offsets,
                scale, block_q, block_k, interpret):
    o, _ = _vfwd_pallas(q, k, v, seg_q, seg_k, pos_q, pos_k, causal,
                        same_offsets, scale, block_q, block_k, interpret)
    return o


def _varlen_mha_fwd(q, k, v, seg_q, seg_k, pos_q, pos_k, causal,
                    same_offsets, scale, block_q, block_k, interpret):
    o, lse = _vfwd_pallas(q, k, v, seg_q, seg_k, pos_q, pos_k, causal,
                          same_offsets, scale, block_q, block_k, interpret)
    return o, (q, k, v, seg_q, seg_k, pos_q, pos_k, o, lse)


def _varlen_mha_bwd(causal, same_offsets, scale, block_q, block_k, interpret,
                    res, do):
    q, k, v, seg_q, seg_k, pos_q, pos_k, o, lse = res
    dq, dk, dv = _vbwd_pallas(q, k, v, o, lse, do, seg_q, seg_k, pos_q,
                              pos_k, causal, same_offsets, scale, block_q,
                              block_k, interpret)
    return dq, dk, dv, None, None, None, None


_varlen_mha.defvjp(_varlen_mha_fwd, _varlen_mha_bwd)


# ---------------------------------------------------------------------------
# Public surfaces
# ---------------------------------------------------------------------------
def flash_attention_varlen(q, k, v, seg_q, seg_k, causal=False, sm_scale=None,
                           block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                           use_pallas=None, interpret=None,
                           same_offsets=None):
    """Packed-layout attention with segment-id masking.

    q: (Tq, H, D); k/v: (Tk, H_kv, D); seg ids (Tq,)/(Tk,) int32 where
    tokens of the same sequence share an id (monotone non-decreasing for
    causal). Causal masking is bottom-right aligned per segment (flash-
    attention semantics when a segment has more k than q tokens).
    `same_offsets=True` (auto when seg_q is seg_k) additionally enables
    the above-diagonal block skip. Returns (Tq, H, D).
    """
    tq, hq, d = q.shape
    tk, hk, _ = k.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if same_offsets is None:
        same_offsets = seg_q is seg_k
    if hk != hq:  # GQA
        k = jnp.repeat(k, hq // hk, axis=1)
        v = jnp.repeat(v, hq // hk, axis=1)
    qh = jnp.swapaxes(q, 0, 1)  # (H, Tq, D)
    kh = jnp.swapaxes(k, 0, 1)
    vh = jnp.swapaxes(v, 0, 1)
    # distinct pad sentinels per side: ANY negative seg id is padding, and
    # q-pads (-1) must never match k-pads (-2) — otherwise pad rows attend
    # pad keys and contaminate outputs/grads at pad positions
    seg_q = jnp.where(seg_q < 0, -1, seg_q).astype(jnp.int32)
    seg_k = jnp.where(seg_k < 0, -2, seg_k).astype(jnp.int32)
    if use_pallas is None:
        use_pallas = _on_tpu()
    if interpret is None:
        interpret = not _on_tpu()
    if not use_pallas and not interpret:
        o, _ = varlen_reference(qh, kh, vh, seg_q, seg_k, causal, scale)
        return jnp.swapaxes(o, 0, 1)
    pos_q = rev_pos(seg_q)
    pos_k = rev_pos(seg_k)
    # blocks must honor the (8, 128) min tile; round small inputs up
    block_q = min(block_q, -(-max(tq, 1) // SUBLANES) * SUBLANES)
    block_k = min(block_k, -(-max(tk, 1) // LANES) * LANES)
    tq_p = -(-tq // block_q) * block_q
    tk_p = -(-tk // block_k) * block_k
    o = _varlen_mha(
        _pad_to(qh, tq_p, 1), _pad_to(kh, tk_p, 1), _pad_to(vh, tk_p, 1),
        _pad_to(seg_q, tq_p, 0, value=-1), _pad_to(seg_k, tk_p, 0, value=-2),
        _pad_to(pos_q, tq_p, 0), _pad_to(pos_k, tk_p, 0),
        causal, same_offsets, scale, block_q, block_k, interpret)
    return jnp.swapaxes(o[:, :tq], 0, 1)


def seg_ids_from_cu_seqlens(cu_seqlens, total):
    """cu_seqlens: (B+1,) int32 prefix sums → (total,) segment ids; tokens
    past cu_seqlens[-1] get -1 (never matched against k's -2 padding)."""
    pos = jnp.arange(total, dtype=jnp.int32)
    seg = jnp.searchsorted(cu_seqlens.astype(jnp.int32)[1:], pos,
                           side="right").astype(jnp.int32)
    return jnp.where(pos < cu_seqlens[-1], seg, -1)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False, return_softmax=False,
                        training=True, name=None, use_pallas=None,
                        interpret=None):
    """Paddle-compatible varlen attention
    (python/paddle/nn/functional/flash_attention.py:756).

    query: (total_q, H, D) packed across the batch; cu_seqlens_q/k:
    (B+1,) token-offset prefix sums. Returns (out, softmax) with
    softmax None (kernel never materializes it).
    """
    tq = query.shape[0]
    tk = key.shape[0]
    same = cu_seqlens_q is cu_seqlens_k
    if not same:
        try:  # static equality also enables the diagonal skip
            same = bool(np.array_equal(np.asarray(cu_seqlens_q),
                                       np.asarray(cu_seqlens_k)))
        except Exception:
            same = False
    seg_q = seg_ids_from_cu_seqlens(jnp.asarray(cu_seqlens_q), tq)
    seg_k = seg_ids_from_cu_seqlens(jnp.asarray(cu_seqlens_k), tk)
    if dropout > 0.0 and training:
        # reference-kernel semantics drop attention *probabilities*, not
        # outputs; the pallas kernel has no in-kernel PRNG, so take the
        # XLA path that materializes P and drops its entries.
        # NB: this materializes the (H, Tq, Tk) probability matrix — fine
        # for training-time dropout at moderate lengths, O(T^2) memory at
        # long context (attention dropout is off in llama-class training)
        from .._core.state import prng
        d = query.shape[-1]
        sc = scale if scale is not None else 1.0 / math.sqrt(d)
        hq, hk = query.shape[1], key.shape[1]
        kk, vv = key, value
        if hk != hq:
            kk = jnp.repeat(key, hq // hk, axis=1)
            vv = jnp.repeat(value, hq // hk, axis=1)
        qh = jnp.swapaxes(query, 0, 1)
        kh = jnp.swapaxes(kk, 0, 1)
        s_ = jnp.einsum("hqd,hkd->hqk", qh.astype(jnp.float32),
                        kh.astype(jnp.float32)) * sc
        # same distinct pad sentinels as the kernel path: q-pads must not
        # match k-pads
        seg_q = jnp.where(seg_q < 0, -1, seg_q)
        seg_k = jnp.where(seg_k < 0, -2, seg_k)
        valid = seg_q[:, None] == seg_k[None, :]
        if causal:
            valid = valid & (rev_pos(seg_k)[None, :] >=
                             rev_pos(seg_q)[:, None])
        s_ = jnp.where(valid[None], s_, NEG_INF)
        pmat = jax.nn.softmax(s_, axis=-1)
        pmat = jnp.where(valid[None], pmat, 0.0)
        keep = jax.random.bernoulli(prng.next_key(), 1.0 - dropout,
                                    pmat.shape)
        pmat = jnp.where(keep, pmat / (1.0 - dropout), 0.0)
        oh = jnp.einsum("hqk,khd->hqd", pmat, vv.astype(jnp.float32))
        return (jnp.swapaxes(oh, 0, 1).astype(query.dtype), None)
    out = flash_attention_varlen(query, key, value, seg_q, seg_k,
                                 causal=causal, sm_scale=scale,
                                 use_pallas=use_pallas, interpret=interpret,
                                 same_offsets=same)
    return (out, None)
