"""paddle_tpu.optimizer (reference: python/paddle/optimizer/__init__.py)."""
from .optimizer import Optimizer  # noqa: F401
from .rules import (  # noqa: F401
    SGD, Momentum, Adam, AdamW, Adamax, Adagrad, Adadelta, RMSProp, Lamb,
    NAdam, RAdam, ASGD, Rprop, Lion, LBFGS, LarsMomentum,
)
from . import lr  # noqa: F401
