"""Optimizer base (reference: python/paddle/optimizer/optimizer.py).

Every optimizer is defined by a *pure* per-parameter update rule
`_rule(p, g, slots, lr) → (new_p, new_slots)` over raw jnp arrays.
The imperative `step()` (paddle dygraph parity) and the functional
`apply_gradients()` (compiled pjit training path) share that rule, so
eager and compiled training are bit-identical.

Multi-precision: bf16/fp16 params keep fp32 master weights in slots
(reference: multi_precision flag on phi optimizer kernels).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np
import jax
import jax.numpy as jnp

from .._core import dtypes as _dt
from .._core.tensor import Parameter, Tensor
from ..regularizer import L1Decay, L2Decay


class Optimizer:
    _slot_names: tuple = ()

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False, **kwargs):
        from .lr import LRScheduler
        self._parameter_list = list(parameters) if parameters is not None else None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            # param groups
            self._param_groups = self._parameter_list
            flat = []
            for g in self._param_groups:
                flat.extend(g["params"])
            self._parameter_list = flat
        else:
            self._param_groups = None
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if isinstance(weight_decay, float):
            self._regularization = L2Decay(weight_decay)
            self._weight_decay = weight_decay
        elif isinstance(weight_decay, (L1Decay, L2Decay)):
            self._regularization = weight_decay
            self._weight_decay = weight_decay.coeff
        else:
            self._regularization = None
            self._weight_decay = 0.0
        self._accumulators: dict = {}
        self._global_step = 0

    # ------------------------------------------------------------------ lr
    def get_lr(self):
        from .lr import LRScheduler
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        from .lr import LRScheduler
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ------------------------------------------------------------- slots
    def _create_slots(self, p):
        """Default: zeros_like fp32 slot per name + step counter."""
        slots = {name: jnp.zeros_like(p, dtype=jnp.float32)
                 for name in self._slot_names}
        slots["step"] = jnp.zeros((), jnp.int32)
        if self._multi_precision and p.dtype in (jnp.bfloat16, jnp.float16):
            slots["master"] = p.astype(jnp.float32)
        return slots

    def _rule(self, p, g, slots, lr):
        raise NotImplementedError

    def _apply_one(self, p_raw, g_raw, slots, lr, param_lr=1.0, regularizer=None):
        """Shared pure update incl. master weights + l1/l2 decay-on-grad."""
        reg = regularizer if regularizer is not None else self._regularization
        work = slots.get("master", p_raw)
        g32 = g_raw.astype(jnp.float32) if work.dtype == jnp.float32 else g_raw
        if reg is not None and not isinstance(self, _DecoupledWeightDecayMixin):
            g32 = reg(work.astype(g32.dtype), g32)
        slots = dict(slots)
        slots["step"] = slots["step"] + 1
        new_work, slots = self._rule(work, g32, slots, lr * param_lr)
        if "master" in slots:
            slots["master"] = new_work
            new_p = new_work.astype(p_raw.dtype)
        else:
            new_p = new_work.astype(p_raw.dtype)
        return new_p, slots

    # ------------------------------------------------------ imperative API
    def step(self):
        params = [p for p in self._parameter_list
                  if isinstance(p, Parameter) and not p.stop_gradient]
        params_grads = [(p, p.grad) for p in params if p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        for p, g in params_grads:
            key = id(p)
            if key not in self._accumulators:
                self._accumulators[key] = self._create_slots(p._value)
            param_lr = p.optimize_attr.get("learning_rate", 1.0) \
                if hasattr(p, "optimize_attr") else 1.0
            new_p, self._accumulators[key] = self._apply_one(
                p._value, g._value, self._accumulators[key], lr, param_lr,
                regularizer=getattr(p, "regularizer", None) or self._regularization)
            p._replace(new_p)
        self._global_step += 1

    minimize_step = step

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list or []]

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list or []:
            p.grad = None

    clear_gradients = clear_grad

    # ------------------------------------------------------ functional API
    def init_state(self, params_tree):
        """params_tree: pytree of raw arrays → state pytree."""
        return jax.tree_util.tree_map(lambda p: self._create_slots(p), params_tree)

    def apply_gradients(self, params_tree, grads_tree, state_tree, lr=None):
        """Pure update over pytrees; jit/pjit-safe. lr may be traced."""
        lr = self.get_lr() if lr is None else lr

        def upd(p, g, slots):
            return self._apply_one(p, g, slots, lr)

        flat_p, treedef = jax.tree_util.tree_flatten(params_tree)
        flat_g = jax.tree_util.tree_flatten(grads_tree)[0]
        flat_s = treedef.flatten_up_to(state_tree)
        new_p, new_s = [], []
        for p, g, s in zip(flat_p, flat_g, flat_s):
            np_, ns_ = upd(p, g, s)
            new_p.append(np_)
            new_s.append(ns_)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                jax.tree_util.tree_unflatten(treedef, new_s))

    # -------------------------------------------------------- state dict
    def state_dict(self):
        sd = OrderedDict()
        for i, p in enumerate(self._parameter_list or []):
            acc = self._accumulators.get(id(p))
            if acc is None:
                continue
            for k, v in acc.items():
                sd[f"{p.name or i}_{k}"] = Tensor(v)
        from .lr import LRScheduler
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        sd["@global_step"] = self._global_step
        return sd

    def set_state_dict(self, state_dict):
        from .lr import LRScheduler
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate,
                                                       LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        self._global_step = state_dict.get("@global_step", 0)
        for i, p in enumerate(self._parameter_list or []):
            prefix = f"{p.name or i}_"
            acc = {}
            for k, v in state_dict.items():
                if isinstance(k, str) and k.startswith(prefix):
                    raw = v._value if isinstance(v, Tensor) else jnp.asarray(v)
                    acc[k[len(prefix):]] = raw
            if acc:
                self._accumulators[id(p)] = acc

    load_state_dict = set_state_dict


class _DecoupledWeightDecayMixin:
    """Marker: weight decay applied in rule (AdamW/Lamb/Lion style)."""
