"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,
adam,adamw,...}.py). Each is only its pure update rule."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer, _DecoupledWeightDecayMixin


class SGD(Optimizer):
    def _rule(self, p, g, slots, lr):
        return p - lr * g, slots


class Momentum(Optimizer):
    _slot_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _rule(self, p, g, slots, lr):
        v = self._momentum * slots["velocity"] + g
        slots["velocity"] = v
        if self._use_nesterov:
            return p - lr * (g + self._momentum * v), slots
        return p - lr * v, slots


class Adam(Optimizer):
    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, name=None,
                 amsgrad=False, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad
        if amsgrad:
            self._slot_names = ("moment1", "moment2", "moment2_max")

    def _rule(self, p, g, slots, lr):
        t = slots["step"].astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g * g
        slots["moment1"], slots["moment2"] = m, v
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        if self._amsgrad:
            vmax = jnp.maximum(slots["moment2_max"], vhat)
            slots["moment2_max"] = vmax
            vhat = vmax
        return p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon), slots


class AdamW(Adam, _DecoupledWeightDecayMixin):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None, amsgrad=False, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name=name,
                         amsgrad=amsgrad)
        self._coeff = weight_decay if isinstance(weight_decay, float) else \
            getattr(weight_decay, "coeff", 0.01)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _rule(self, p, g, slots, lr):
        p = p * (1.0 - lr * self._coeff)
        return super()._rule(p, g, slots, lr)


class Adamax(Optimizer):
    _slot_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _rule(self, p, g, slots, lr):
        t = slots["step"].astype(jnp.float32)
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g))
        slots["moment"], slots["inf_norm"] = m, u
        return p - (lr / (1 - self._beta1 ** t)) * m / (u + self._epsilon), slots


class Adagrad(Optimizer):
    _slot_names = ("moment",)

    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        slots["moment"] = jnp.full_like(p, self._init_acc, dtype=jnp.float32)
        return slots

    def _rule(self, p, g, slots, lr):
        acc = slots["moment"] + g * g
        slots["moment"] = acc
        return p - lr * g / (jnp.sqrt(acc) + self._epsilon), slots


class Adadelta(Optimizer):
    _slot_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _rule(self, p, g, slots, lr):
        sq = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * g * g
        upd = jnp.sqrt(slots["avg_squared_update"] + self._epsilon) / \
            jnp.sqrt(sq + self._epsilon) * g
        slots["avg_squared_grad"] = sq
        slots["avg_squared_update"] = self._rho * slots["avg_squared_update"] + \
            (1 - self._rho) * upd * upd
        return p - lr * upd, slots


class RMSProp(Optimizer):
    _slot_names = ("mean_square", "mean_grad", "momentum_acc")

    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _rule(self, p, g, slots, lr):
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * g * g
        slots["mean_square"] = ms
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g
            slots["mean_grad"] = mg
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * slots["momentum_acc"] + lr * g / denom
        slots["momentum_acc"] = mom
        return p - mom, slots


class Lamb(Optimizer, _DecoupledWeightDecayMixin):
    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _rule(self, p, g, slots, lr):
        t = slots["step"].astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g * g
        slots["moment1"], slots["moment2"] = m, v
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._lamb_wd * p
        w_norm = jnp.sqrt(jnp.sum(p * p))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, slots


class LarsMomentum(Optimizer):
    """Layer-wise adaptive rate scaling + momentum (reference:
    python/paddle/incubate/optimizer/lars_momentum.py:25,
    paddle/phi/kernels/gpu/lars_momentum_kernel.cu):

        local_lr = lr * lars_coeff * ||p|| /
                   (||g|| + lars_weight_decay * ||p|| + eps)
        v        = mu * v + local_lr * (g + lars_weight_decay * p)
        p        = p - v

    (epsilon guards the local_lr division, per the reference's
    documented purpose "avoid Division by Zero when calculate local
    lr" — its docstring typesets eps inside the velocity term, but the
    division guard is the semantic.)

    The reference's per-layer exclude_from_weight_decay name list is
    not carried here (the functional rule sees arrays, not names);
    construct a second LarsMomentum(lars_weight_decay=0.0) for the
    excluded parameter group instead.
    """
    _slot_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, regularization=None,
                 grad_clip=None, name=None, epsilon=0.0, multi_precision=False,
                 rescale_grad=1.0, **kw):
        super().__init__(learning_rate, parameters, regularization, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._epsilon = epsilon
        self._rescale_grad = rescale_grad

    def _rule(self, p, g, slots, lr):
        g = g * self._rescale_grad
        p_norm = jnp.sqrt(jnp.sum(p.astype(jnp.float32) ** 2))
        g_norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        denom = g_norm + self._lars_wd * p_norm + self._epsilon
        # gate on g_norm (not denom): the reference kernel falls back to
        # plain lr when EITHER norm is zero, so a zero-grad param decays
        # at lr*wd, not at the coeff/wd-scaled rate
        local_lr = jnp.where((p_norm > 0) & (g_norm > 0),
                             lr * self._lars_coeff * p_norm /
                             jnp.maximum(denom, 1e-30), lr)
        v = self._momentum * slots["velocity"] \
            + local_lr * (g + self._lars_wd * p)
        slots["velocity"] = v
        return p - v, slots


class NAdam(Optimizer):
    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 momentum_decay=0.004, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        slots["mu_product"] = jnp.ones((), jnp.float32)
        return slots

    def _rule(self, p, g, slots, lr):
        t = slots["step"].astype(jnp.float32)
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod = slots["mu_product"] * mu_t
        slots["mu_product"] = mu_prod
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g * g
        slots["moment1"], slots["moment2"] = m, v
        mhat = mu_t1 * m / (1 - mu_prod * mu_t1) + (1 - mu_t) * g / (1 - mu_prod)
        vhat = v / (1 - self._beta2 ** t)
        return p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon), slots


class RAdam(Optimizer):
    _slot_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _rule(self, p, g, slots, lr):
        t = slots["step"].astype(jnp.float32)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g * g
        slots["moment1"], slots["moment2"] = m, v
        mhat = m / (1 - self._beta1 ** t)
        rho_inf = 2.0 / (1 - self._beta2) - 1
        rho_t = rho_inf - 2 * t * self._beta2 ** t / (1 - self._beta2 ** t)
        vhat = jnp.sqrt(v / (1 - self._beta2 ** t))
        r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf) /
                     jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-8))
        upd = jnp.where(rho_t > 5.0, r * mhat / (vhat + self._epsilon), mhat)
        return p - lr * upd, slots


class ASGD(Optimizer):
    _slot_names = ("d", "ys")

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         multi_precision)
        self._batch_num = batch_num

    def _rule(self, p, g, slots, lr):
        # simplified averaged-SGD accumulation
        d = slots["d"] - slots["ys"] + g
        slots["d"] = d
        slots["ys"] = g
        return p - lr / self._batch_num * d, slots


class Rprop(Optimizer):
    _slot_names = ("prev_grad", "lr_slot")

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_minus, self._eta_plus = etas

    def _create_slots(self, p):
        slots = super()._create_slots(p)
        slots["lr_slot"] = jnp.full_like(p, self.get_lr(), dtype=jnp.float32)
        return slots

    def _rule(self, p, g, slots, lr):
        sign = jnp.sign(g * slots["prev_grad"])
        factor = jnp.where(sign > 0, self._eta_plus,
                           jnp.where(sign < 0, self._eta_minus, 1.0))
        lrs = jnp.clip(slots["lr_slot"] * factor, self._lr_min, self._lr_max)
        g_eff = jnp.where(sign < 0, 0.0, g)
        slots["prev_grad"] = g_eff
        slots["lr_slot"] = lrs
        return p - lrs * jnp.sign(g_eff), slots


class Lion(Optimizer, _DecoupledWeightDecayMixin):
    """Lion (extra vs reference — common in TPU training stacks)."""

    _slot_names = ("moment",)

    def __init__(self, learning_rate=1e-4, beta1=0.9, beta2=0.99, parameters=None,
                 weight_decay=0.0, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2 = beta1, beta2
        self._coeff = weight_decay

    def _rule(self, p, g, slots, lr):
        m = slots["moment"]
        update = jnp.sign(self._beta1 * m + (1 - self._beta1) * g)
        slots["moment"] = self._beta2 * m + (1 - self._beta2) * g
        p = p * (1 - lr * self._coeff)
        return p - lr * update, slots


class LBFGS(Optimizer):
    """Minimal L-BFGS with closure (reference: python/paddle/optimizer/lbfgs.py).

    History-based two-loop recursion; eager-only (uses closure re-eval)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._max_iter = max_iter
        self._history_size = history_size
        self._s, self._y = [], []
        self._prev_flat_grad = None
        self._prev_flat_param = None

    def _flat(self, vals):
        return jnp.concatenate([v.reshape(-1).astype(jnp.float32) for v in vals])

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure")
        loss = closure()
        params = [p for p in self._parameter_list if not p.stop_gradient]
        grads = [p.grad._value if p.grad is not None else jnp.zeros_like(p._value)
                 for p in params]
        flat_g = self._flat(grads)
        flat_p = self._flat([p._value for p in params])
        if self._prev_flat_grad is not None:
            s = flat_p - self._prev_flat_param
            y = flat_g - self._prev_flat_grad
            if float(jnp.dot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self._history_size:
                    self._s.pop(0)
                    self._y.pop(0)
        q = flat_g
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.dot(y, s)
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append((a, rho))
        if self._s:
            gamma = jnp.dot(self._s[-1], self._y[-1]) / \
                jnp.dot(self._y[-1], self._y[-1])
            q = gamma * q
        for (a, rho), s, y in zip(reversed(alphas), self._s, self._y):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        direction = -q
        lr = self.get_lr()
        offset = 0
        for p in params:
            n = p._value.size
            upd = direction[offset:offset + n].reshape(p._value.shape)
            p._replace((p._value.astype(jnp.float32) + lr * upd).astype(p.dtype))
            offset += n
        self._prev_flat_grad = flat_g
        self._prev_flat_param = flat_p + lr * direction
        return loss
