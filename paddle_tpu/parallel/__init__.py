"""paddle_tpu.parallel: TPU-native parallelism core.

Mesh + GSPMD sharding (tp/dp/fsdp), shard_map pipelines (pp), ring
attention (sp/context parallel), MoE expert parallel (ep), and the
compiled hybrid-parallel Trainer. The paddle-compatible fleet API in
paddle_tpu.distributed.fleet delegates here.
"""
from .mesh import create_mesh, get_mesh, sharding_for, replicated, fsdp_spec  # noqa: F401
from .trainer import Trainer  # noqa: F401
from .tp import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, annotate_module_tp, mark_sequence_parallel,
)
from .pp import pipeline_apply, stack_layer_params, group_stages, LayerDesc, \
    PipelineLayer  # noqa: F401
from .ring import ring_attention, ring_attention_local, sequence_shard  # noqa: F401
from .ulysses import ulysses_attention, ulysses_attention_local  # noqa: F401
from .moe import MoELayer, moe_ffn_apply, top_k_gating  # noqa: F401
