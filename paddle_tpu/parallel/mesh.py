"""Device mesh construction (replaces fleet's HybridCommunicateGroup
topology over NCCL groups — reference: python/paddle/distributed/fleet/
base/topology.py — with a jax.sharding.Mesh over ICI).

Axis convention (outer→inner, matching ICI locality preferences):
  pp (slowest, smallest traffic) → dp → fsdp/sharding → sp/ep → tp (fastest,
  biggest collectives ride the innermost ICI ring).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

STANDARD_AXES = ("pp", "dp", "tp")


def create_mesh(axes=None, devices=None, **axis_sizes):
    """create_mesh({'dp': 2, 'tp': 4}) or create_mesh(dp=2, tp=4).

    Unspecified leftover devices fold into 'dp'. -1 on one axis = infer.
    """
    if axes is None:
        axes = dict(axis_sizes)
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    names = list(axes.keys())
    sizes = [int(v) for v in axes.values()]
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        if n % total == 0:
            names.insert(0, "dp") if "dp" not in names else None
            if "dp" in axes:
                raise ValueError(f"mesh {axes} does not cover {n} devices")
            sizes.insert(0, n // total)
        else:
            raise ValueError(f"mesh sizes {axes} incompatible with {n} devices")
    mesh = Mesh(devices.reshape(sizes), tuple(names))
    from ..distributed import env
    env.set_global_mesh(mesh)
    return mesh


def get_mesh():
    from ..distributed import env
    return env.get_global_mesh()


def sharding_for(mesh, spec):
    return NamedSharding(mesh, spec if isinstance(spec, P) else P(*spec))


def replicated(mesh):
    return NamedSharding(mesh, P())


def axis_size(mesh, name):
    return mesh.shape[name] if name in mesh.shape else 1


def fsdp_spec(shape, mesh, axis="dp", min_size=1024):
    """FSDP/ZeRO-3 param spec: shard the largest axis divisible by the dp
    axis size (XLA all-gathers on use — ZeRO semantics via GSPMD)."""
    if axis not in mesh.shape:
        return P()
    n = mesh.shape[axis]
    size = int(np.prod(shape)) if shape else 0
    if size < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda i: -shape[i])
    for d in dims:
        if shape[d] % n == 0:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()
