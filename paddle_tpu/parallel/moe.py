"""Expert parallelism / MoE (reference: python/paddle/incubate/nn/layer/
fused_moe + fleet EP groups over NCCL alltoall).

TPU-native GShard-style dense dispatch: top-k gating → capacity-bounded
one-hot dispatch tensors → two einsums. With the expert axis sharded
over 'ep' on the mesh, GSPMD lowers the dispatch einsums to all_to_all
over ICI — the NCCL alltoall of the reference, derived not hand-written.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .._core.tensor import Tensor, apply
from ..nn.layer.layers import Layer
from ..nn.initializer import XavierUniform


def expert_slot_positions(topk_idx, tot_expert):
    """(T, k) expert ids (negatives = dropped) → (T, k) arrival rank of
    each assignment within its expert's queue, slot-major (slot 0 of
    every token first). THE shared rank computation for every
    capacity-bounded dispatch in the tree (this module's fused gating,
    incubate MoELayer's dispatch, the gshard gate's capacity limiter) —
    the `-1` must apply after reducing the hot column, a pitfall that
    has produced slot-collision bugs when re-derived by hand."""
    T, k = topk_idx.shape
    flat = jnp.where(topk_idx >= 0, topk_idx, tot_expert
                     ).transpose(1, 0).reshape(-1)
    onehot = jax.nn.one_hot(flat, tot_expert + 1, dtype=jnp.int32)
    rank = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1) - 1
    return rank.reshape(k, T).transpose(1, 0)


def top_k_gating(logits, k, capacity, expert_axis_size=1):
    """logits (T, E) → dispatch (T, E, C) bool, combine (T, E, C) float,
    aux_loss (load-balance, Switch-style)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # (T, k)
    # renormalize chosen gates
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    # position of each token within its expert queue (per chosen slot)
    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # (T, k, E)
    # flatten slots in priority order: slot 0 of all tokens first
    flat = onehot.transpose(1, 0, 2).reshape(k * T, E)
    pos_in_expert = jnp.cumsum(flat, axis=0) * flat - 1  # (k*T, E)
    pos = pos_in_expert.reshape(k, T, E).transpose(1, 0, 2)  # (T, k, E)
    pos_tok = jnp.sum(pos * onehot, axis=-1)  # (T, k)
    keep = (pos_tok < capacity) & (pos_tok >= 0)

    # (T, k, E, C): expert one-hot × capacity-slot one-hot per chosen slot
    disp = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)[..., None] * \
        jax.nn.one_hot(jnp.clip(pos_tok, 0, capacity - 1), capacity,
                       dtype=jnp.float32)[..., None, :]
    disp = disp * keep[..., None, None].astype(jnp.float32)
    dispatch = jnp.sum(disp, axis=1)  # (T, E, C) 0/1
    combine = jnp.sum(disp * gate_vals[..., None, None], axis=1)  # (T, E, C)

    # load-balance aux loss (Switch): E * sum(me * ce)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32), axis=0)
    aux = E * jnp.sum(me * ce)
    return dispatch, combine, aux


def moe_ffn_apply(x_tokens, gate_w, expert_ws, k=2, capacity_factor=1.25,
                  ep_axis="ep", mesh=None, activation=jax.nn.silu):
    """Pure MoE forward over raw arrays.

    x_tokens: (T, M); gate_w: (M, E);
    expert_ws: dict(w_gate (E,M,F), w_up (E,M,F) [optional], w_down (E,F,M))
    Returns (T, M), aux_loss.
    """
    T, M = x_tokens.shape
    E = gate_w.shape[1]
    capacity = max(1, int(capacity_factor * T * k / E))
    logits = x_tokens.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    dispatch, combine, aux = top_k_gating(logits, k, capacity)
    # dispatch: (T,E,C) → expert inputs (E, C, M); GSPMD all_to_all if E sharded
    expert_in = jnp.einsum("tec,tm->ecm", dispatch.astype(x_tokens.dtype),
                           x_tokens)
    if mesh is not None and ep_axis in mesh.shape:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, jax.sharding.NamedSharding(mesh, P(ep_axis, None, None)))

    wg = expert_ws["w_gate"]
    wd = expert_ws["w_down"]
    wu = expert_ws.get("w_up")
    h = jnp.einsum("ecm,emf->ecf", expert_in, wg)
    if wu is not None:
        u = jnp.einsum("ecm,emf->ecf", expert_in, wu)
        h = activation(h) * u
    else:
        h = activation(h)
    expert_out = jnp.einsum("ecf,efm->ecm", h, wd)
    if mesh is not None and ep_axis in mesh.shape:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, jax.sharding.NamedSharding(mesh, P(ep_axis, None, None)))
    out = jnp.einsum("tec,ecm->tm", combine.astype(x_tokens.dtype), expert_out)
    return out, aux


class MoELayer(Layer):
    """Mixture-of-experts FFN (SwiGLU experts + optional shared experts —
    DeepSeekMoE/Qwen2-MoE shape; reference: incubate FusedMoE)."""

    def __init__(self, d_model, d_ff, num_experts, top_k=2, capacity_factor=1.25,
                 num_shared_experts=0, ep_axis="ep", gate_attr=None, name=None):
        super().__init__()
        self.d_model = d_model
        self.d_ff = d_ff
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        init = XavierUniform()
        self.gate_weight = self.create_parameter([d_model, num_experts],
                                                 attr=gate_attr,
                                                 default_initializer=init)
        self.w_gate = self.create_parameter([num_experts, d_model, d_ff],
                                            default_initializer=init)
        self.w_up = self.create_parameter([num_experts, d_model, d_ff],
                                          default_initializer=init)
        self.w_down = self.create_parameter([num_experts, d_ff, d_model],
                                            default_initializer=init)
        for p in (self.w_gate, self.w_up, self.w_down):
            p.dist_spec = P(ep_axis)
            p.is_distributed = True
        if num_shared_experts > 0:
            self.shared_gate = self.create_parameter(
                [d_model, d_ff * num_shared_experts], default_initializer=init)
            self.shared_up = self.create_parameter(
                [d_model, d_ff * num_shared_experts], default_initializer=init)
            self.shared_down = self.create_parameter(
                [d_ff * num_shared_experts, d_model], default_initializer=init)
        else:
            self.shared_gate = None
        self.aux_loss = None

    def forward(self, x):
        from .mesh import get_mesh
        mesh = get_mesh()
        shape = x.shape

        def fn(xr, gw, wg, wu, wd, *shared):
            tokens = xr.reshape(-1, shape[-1])
            out, aux = moe_ffn_apply(
                tokens, gw, {"w_gate": wg, "w_up": wu, "w_down": wd},
                k=self.top_k, capacity_factor=self.capacity_factor,
                ep_axis=self.ep_axis, mesh=mesh)
            if shared:
                sg, su, sd = shared
                s = (jax.nn.silu(tokens @ sg) * (tokens @ su)) @ sd
                out = out + s
            return out.reshape(xr.shape), aux

        args = [x, self.gate_weight, self.w_gate, self.w_up, self.w_down]
        if self.shared_gate is not None:
            args += [self.shared_gate, self.shared_up, self.shared_down]
        out, aux = apply(fn, *args, name="moe", multi=True)
        self.aux_loss = aux
        return out
