"""Pipeline parallelism (reference: python/paddle/distributed/fleet/
meta_parallel/pipeline_parallel.py — GPipe/1F1B over NCCL p2p).

TPU-native: the pipeline is ONE differentiable SPMD program —
shard_map over the 'pp' mesh axis, lax.scan over microbatch ticks,
lax.ppermute moving activations around the ICI ring. JAX reverse-mode AD
through ppermute/scan yields the backward pipeline automatically (no
hand-written 1F1B schedule or send/recv state machine). Other mesh axes
(dp/tp/sp) remain GSPMD-auto inside each stage.

Requires homogeneous stages: per-layer params stacked on a leading axis,
grouped (n_stages, layers_per_stage, ...).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def stack_layer_params(layer_params_list):
    """[{name: array} per layer] → {name: array stacked on axis 0}."""
    keys = layer_params_list[0].keys()
    return {k: jnp.stack([lp[k] for lp in layer_params_list]) for k in keys}


def group_stages(stacked, n_stages):
    """{name: (L, ...)} → {name: (n_stages, L/n_stages, ...)}."""
    def regroup(a):
        L = a.shape[0]
        assert L % n_stages == 0, f"layers {L} not divisible by pp={n_stages}"
        return a.reshape(n_stages, L // n_stages, *a.shape[1:])
    return jax.tree_util.tree_map(regroup, stacked)


def pipeline_apply(stage_params, x, layer_fn, mesh, pp_axis="pp", n_micro=None,
                   extra=None):
    """Differentiable GPipe forward.

    stage_params: pytree, leaves (n_stages, layers_per_stage, ...) —
      sharded over pp on axis 0.
    x: (B, ...) activations entering stage 0 (replicated over pp).
    layer_fn(layer_params, h, extra) → h : one transformer layer.
    extra: static per-call aux (e.g. rope tables), replicated.
    Returns activations after the last stage, replicated over pp.
    """
    n_stages = mesh.shape[pp_axis]
    B = x.shape[0]
    if n_micro is None:
        n_micro = n_stages
    assert B % n_micro == 0, f"batch {B} not divisible by n_micro {n_micro}"
    mb = B // n_micro
    x_micro = x.reshape(n_micro, mb, *x.shape[1:])

    def stage_fn(params_local, h, extra_):
        # params_local leaves: (layers_per_stage, ...) → scan over layers
        def body(carry, layer_params):
            return layer_fn(layer_params, carry, extra_), None
        out, _ = lax.scan(body, h, params_local)
        return out

    def per_rank(params_shard, xm, extra_):
        # params_shard leaves: (1, layers_per_stage, ...) local shard
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_shard)
        idx = lax.axis_index(pp_axis)
        total = n_micro + n_stages - 1
        buf0 = jnp.zeros_like(xm[0])
        out0 = jnp.zeros_like(xm)

        def tick(carry, t):
            buf, outs = carry
            inp = jnp.where(idx == 0,
                            xm[jnp.clip(t, 0, n_micro - 1)], buf)
            y = stage_fn(params_local, inp, extra_)
            m = t - (n_stages - 1)
            write = (idx == n_stages - 1) & (m >= 0)
            outs = lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(write, y, lax.dynamic_index_in_dim(
                    outs, jnp.clip(m, 0, n_micro - 1), 0, keepdims=False)),
                jnp.clip(m, 0, n_micro - 1), 0)
            nxt = lax.ppermute(y, pp_axis,
                               [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (nxt, outs), None

        (_, outs), _ = lax.scan(tick, (buf0, out0), jnp.arange(total))
        # replicate result from the last stage to all pp ranks
        outs = lax.psum(jnp.where(idx == n_stages - 1, outs,
                                  jnp.zeros_like(outs)), pp_axis)
        return outs

    mapped = jax.shard_map(
        per_rank, mesh=mesh,
        in_specs=(P(pp_axis), P(), P()),
        out_specs=P(),
        axis_names=frozenset({pp_axis}),
        check_vma=False)
    out = mapped(stage_params, x_micro, extra if extra is not None else jnp.zeros(()))
    return out.reshape(B, *out.shape[2:])


class LayerDesc:
    """reference: fleet.meta_parallel LayerDesc."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *args, forward_func=None, shared_weight_attr=None,
                 **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key


class PipelineLayer:
    """API-parity container (reference: fleet.meta_parallel.PipelineLayer):
    splits a LayerDesc list into pp stages. The compiled path uses
    pipeline_apply on stacked homogeneous blocks; heterogeneous head/tail
    run replicated outside the pp loop."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        self.descs = layers
        self.num_stages = num_stages or 1
        self.loss_fn = loss_fn
        self.built = [d.build() if isinstance(d, LayerDesc) else d
                      for d in layers]

    def forward(self, x):
        for l in self.built:
            x = l(x)
        return x

    def __call__(self, x):
        return self.forward(x)
