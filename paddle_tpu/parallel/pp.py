"""Pipeline parallelism (reference: python/paddle/distributed/fleet/
meta_parallel/pipeline_parallel.py — GPipe/1F1B over NCCL p2p).

TPU-native: the pipeline is ONE differentiable SPMD program —
shard_map over the 'pp' mesh axis, lax.scan over microbatch ticks,
lax.ppermute moving activations around the ICI ring. JAX reverse-mode AD
through ppermute/scan yields the backward pipeline automatically (no
hand-written 1F1B schedule or send/recv state machine). Other mesh axes
(dp/tp/sp) remain GSPMD-auto inside each stage.

Requires homogeneous stages: per-layer params stacked on a leading axis,
grouped (n_stages, layers_per_stage, ...).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def stack_layer_params(layer_params_list):
    """[{name: array} per layer] → {name: array stacked on axis 0}."""
    keys = layer_params_list[0].keys()
    return {k: jnp.stack([lp[k] for lp in layer_params_list]) for k in keys}


def group_stages(stacked, n_stages):
    """{name: (L, ...)} → {name: (n_stages, L/n_stages, ...)}."""
    def regroup(a):
        L = a.shape[0]
        assert L % n_stages == 0, f"layers {L} not divisible by pp={n_stages}"
        return a.reshape(n_stages, L // n_stages, *a.shape[1:])
    return jax.tree_util.tree_map(regroup, stacked)


def pipeline_apply(stage_params, x, layer_fn, mesh, pp_axis="pp", n_micro=None,
                   extra=None):
    """Differentiable GPipe forward.

    stage_params: pytree, leaves (n_stages, layers_per_stage, ...) —
      sharded over pp on axis 0.
    x: (B, ...) activations entering stage 0 (replicated over pp).
    layer_fn(layer_params, h, extra) → h : one transformer layer.
    extra: static per-call aux (e.g. rope tables), replicated.
    Returns activations after the last stage, replicated over pp.
    """
    n_stages = mesh.shape[pp_axis]
    B = x.shape[0]
    if n_micro is None:
        n_micro = n_stages
    assert B % n_micro == 0, f"batch {B} not divisible by n_micro {n_micro}"
    mb = B // n_micro
    x_micro = x.reshape(n_micro, mb, *x.shape[1:])

    def stage_fn(params_local, h, extra_):
        # params_local leaves: (layers_per_stage, ...) → scan over layers
        def body(carry, layer_params):
            return layer_fn(layer_params, carry, extra_), None
        out, _ = lax.scan(body, h, params_local)
        return out

    def per_rank(params_shard, xm, extra_):
        # params_shard leaves: (1, layers_per_stage, ...) local shard
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_shard)
        idx = lax.axis_index(pp_axis)
        total = n_micro + n_stages - 1
        buf0 = jnp.zeros_like(xm[0])
        out0 = jnp.zeros_like(xm)

        def tick(carry, t):
            buf, outs = carry
            inp = jnp.where(idx == 0,
                            xm[jnp.clip(t, 0, n_micro - 1)], buf)
            # idle-tick skip: stage `idx` only has real work while
            # 0 <= t - idx < n_micro; outside that window the cond's
            # passthrough branch costs nothing instead of computing
            # garbage (VERDICT r2 weak #4: was up to 1.5x wasted FLOPs)
            active = ((t - idx) >= 0) & ((t - idx) < n_micro)
            y = lax.cond(active,
                         lambda h: stage_fn(params_local, h, extra_),
                         lambda h: h, inp)
            m = t - (n_stages - 1)
            write = (idx == n_stages - 1) & (m >= 0)
            outs = lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(write, y, lax.dynamic_index_in_dim(
                    outs, jnp.clip(m, 0, n_micro - 1), 0, keepdims=False)),
                jnp.clip(m, 0, n_micro - 1), 0)
            nxt = lax.ppermute(y, pp_axis,
                               [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (nxt, outs), None

        (_, outs), _ = lax.scan(tick, (buf0, out0), jnp.arange(total))
        # replicate result from the last stage to all pp ranks
        outs = lax.psum(jnp.where(idx == n_stages - 1, outs,
                                  jnp.zeros_like(outs)), pp_axis)
        return outs

    mapped = jax.shard_map(
        per_rank, mesh=mesh,
        in_specs=(P(pp_axis), P(), P()),
        out_specs=P(),
        axis_names=frozenset({pp_axis}),
        check_vma=False)
    out = mapped(stage_params, x_micro, extra if extra is not None else jnp.zeros(()))
    return out.reshape(B, *out.shape[2:])


def pipeline_train_1f1b(stage_params, x, targets, layer_fn, head_fn,
                        head_params, mesh, pp_axis="pp", n_micro=None,
                        extra=None):
    """One-forward-one-backward (PipeDream-flush) pipeline TRAIN pass.

    Reference schedule: python/paddle/distributed/fleet/meta_parallel/
    pipeline_parallel.py:958 (1F1B over NCCL p2p). TPU-native: ONE
    lax.scan over global ticks inside shard_map; each tick runs a
    forward sub-tick and a backward sub-tick, with activations moving
    forward and gradients moving backward over the ICI ring in the same
    step. The backward is hand-seeded (loss computed in-pipeline on the
    last stage via `head_fn`), so only a ring of 2*n_stages stage
    INPUTS is ever stashed — the defining 1F1B property of O(stages)
    activation memory instead of GPipe's O(n_micro) — and each stage's
    backward recomputes its forward from the stashed input (remat).

    Timing: stage s forwards microbatch m at tick t = m + s and
    backwards it at t = m + 2S - 2 - s, so the last stage does fwd(m)
    and bwd(m) in the SAME tick (its head-vjp seeds the backward), and
    every other stage receives the gradient one tick after its
    downstream neighbour produced it. Total ticks = M + 2S - 2; the
    steady state is exactly one forward + one backward per tick.

    Args:
      stage_params: pytree, leaves (n_stages, layers_per_stage, ...),
        sharded over pp on axis 0.
      x: (B, ...) activations entering stage 0 (replicated over pp).
      targets: (B, ...) labels, consumed by head_fn on the last stage.
      layer_fn(layer_params, h, extra) -> h: one transformer layer.
      head_fn(head_params, h, targets_mb) -> (loss_sum, weight) for one
        microbatch (fold final-norm + lm_head + loss here). The
        pipeline's loss is sum(loss_sum) / sum(weight) over all
        microbatches, so with ignore-labels every microbatch is
        weighted by its VALID token count — exactly matching the no-pp
        and grad-accum paths even with unevenly distributed masking.
        For plain mean-loss semantics return (mean_loss, 1.0).
      head_params: pytree, replicated.
    Returns:
      (loss, stage_grads, head_grads, dx) — loss = Σ loss_sum / Σ
      weight; stage_grads matches stage_params' structure/sharding
      (fp32), head_grads matches head_params (fp32, replicated), dx is
      dLoss/dx (B, ...).
    """
    n_stages = mesh.shape[pp_axis]
    B = x.shape[0]
    if n_micro is None:
        n_micro = n_stages
    assert B % n_micro == 0, f"batch {B} not divisible by n_micro {n_micro}"
    mb = B // n_micro
    M, S = n_micro, n_stages
    x_micro = x.reshape(M, mb, *x.shape[1:])
    t_micro = targets.reshape(M, mb, *targets.shape[1:])
    cap = 2 * S  # in-flight stage inputs are consecutive and <= 2S-1
    total = M + 2 * S - 2

    def stage_fn(params_local, h, extra_):
        def body(carry, layer_params):
            return layer_fn(layer_params, carry, extra_), None
        out, _ = lax.scan(body, h, params_local)
        return out

    def per_rank(params_shard, xm, tm, head_p, extra_):
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_shard)
        s = lax.axis_index(pp_axis)
        is_last = s == S - 1

        f32z = functools.partial(jax.tree_util.tree_map,
                                 lambda a: jnp.zeros(a.shape, jnp.float32))
        stash0 = jnp.zeros((cap,) + xm.shape[1:], xm.dtype)
        act0 = jnp.zeros_like(xm[0])
        carry0 = (stash0, act0, act0, f32z(params_local), f32z(head_p),
                  jnp.zeros_like(xm), jnp.zeros((M,), jnp.float32),
                  jnp.zeros((M,), jnp.float32))

        def tick(carry, t):
            stash, fwd_buf, bwd_buf, gparams, ghead, dx, losses, wts = carry

            # ---- forward sub-tick: microbatch mf = t - s
            mf = t - s
            f_active = (mf >= 0) & (mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            inp = jnp.where(s == 0, xm[mf_c], fwd_buf)
            y = lax.cond(f_active,
                         lambda h: stage_fn(params_local, h, extra_),
                         lambda h: h, inp)
            stash = lax.cond(
                f_active,
                lambda st: lax.dynamic_update_index_in_dim(
                    st, inp, mf_c % cap, 0),
                lambda st: st, stash)

            # last stage: head vjp NOW — its gy seeds this tick's
            # backward sub-tick (bwd microbatch == mf on the last stage).
            # The backward is seeded with d/d(loss_sum) = 1; the global
            # 1/Σweight normalization is applied once after the scan.
            def head_grad(args):
                y_, tgt = args
                loss_m, pull, w_m = jax.vjp(
                    lambda hp, yy: head_fn(hp, yy, tgt), head_p, y_,
                    has_aux=True)
                ghp, gy = pull(jnp.float32(1.0))
                return (loss_m, jnp.float32(w_m),
                        jax.tree_util.tree_map(
                            lambda a: a.astype(jnp.float32), ghp),
                        gy.astype(y_.dtype))
            loss_m, w_m, ghp, gy = lax.cond(
                f_active & is_last, head_grad,
                lambda args: (jnp.float32(0.0), jnp.float32(0.0),
                              f32z(head_p), jnp.zeros_like(args[0])),
                (y, tm[mf_c]))
            ghead = jax.tree_util.tree_map(lambda a, b: a + b, ghead, ghp)
            losses = lax.cond(
                f_active & is_last,
                lambda ls: ls.at[mf_c].set(loss_m),
                lambda ls: ls, losses)
            wts = lax.cond(
                f_active & is_last,
                lambda ws: ws.at[mf_c].set(w_m),
                lambda ws: ws, wts)

            # ---- backward sub-tick: microbatch mb_ = t - (2S - 2 - s)
            mb_ = t - (2 * S - 2 - s)
            b_active = (mb_ >= 0) & (mb_ < M)
            mb_c = jnp.clip(mb_, 0, M - 1)
            inp_b = lax.dynamic_index_in_dim(stash, mb_c % cap, 0,
                                             keepdims=False)
            gin = jnp.where(is_last, gy, bwd_buf)

            def bwd(args):
                inp_b_, gin_ = args
                _, pull = jax.vjp(
                    lambda p, h: stage_fn(p, h, extra_),
                    params_local, inp_b_)
                gp, gh = pull(gin_)
                return (jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.float32), gp),
                    gh.astype(gin_.dtype))
            gp, gh = lax.cond(
                b_active, bwd,
                lambda args: (f32z(params_local), jnp.zeros_like(args[1])),
                (inp_b, gin))
            gparams = jax.tree_util.tree_map(lambda a, b: a + b, gparams, gp)
            dx = lax.cond(
                b_active & (s == 0),
                lambda d: lax.dynamic_update_index_in_dim(
                    d, gh.astype(d.dtype), mb_c, 0),
                lambda d: d, dx)

            # ---- ring hops (uniform across ranks — never inside cond)
            fwd_buf = lax.ppermute(
                y, pp_axis, [(i, (i + 1) % S) for i in range(S)])
            bwd_buf = lax.ppermute(
                gh, pp_axis, [(i, (i - 1) % S) for i in range(S)])
            return (stash, fwd_buf, bwd_buf, gparams, ghead, dx,
                    losses, wts), None

        (_, _, _, gparams, ghead, dx, losses, wts), _ = lax.scan(
            tick, carry0, jnp.arange(total))

        # losses/wts live on the last rank, dx on rank 0 — replicate,
        # then normalize everything by the GLOBAL weight sum (valid
        # token count for NLL heads), so uneven ignore-label masking
        # across microbatches matches the no-pp step exactly
        losses = lax.psum(jnp.where(is_last, losses,
                                    jnp.zeros_like(losses)), pp_axis)
        wts = lax.psum(jnp.where(is_last, wts, jnp.zeros_like(wts)),
                       pp_axis)
        inv_w = 1.0 / jnp.maximum(jnp.sum(wts), 1e-9)
        gparams = jax.tree_util.tree_map(
            lambda a: (a * inv_w)[None], gparams)  # re-add stage axis
        ghead = jax.tree_util.tree_map(
            lambda a: lax.psum(a, pp_axis) * inv_w, ghead)
        dx = lax.psum(jnp.where(s == 0, dx, jnp.zeros_like(dx)),
                      pp_axis) * inv_w
        return gparams, ghead, dx, losses, wts

    mapped = jax.shard_map(
        per_rank, mesh=mesh,
        in_specs=(P(pp_axis), P(), P(), P(), P()),
        out_specs=(P(pp_axis), P(), P(), P(), P()),
        axis_names=frozenset({pp_axis}),
        check_vma=False)
    gstage, ghead, dx, losses, wts = mapped(
        stage_params, x_micro, t_micro, head_params,
        extra if extra is not None else jnp.zeros(()))
    loss = jnp.sum(losses) / jnp.maximum(jnp.sum(wts), 1e-9)
    return loss, gstage, ghead, dx.reshape(B, *dx.shape[2:])


def pipeline_bubble_fraction(n_micro, n_stages, schedule="1f1b"):
    """Idle fraction of the tick grid.

    Our lockstep 1F1B burns M + 2S - 2 full fwd+bwd ticks — (S-1) extra
    tick-pairs versus the GPipe-AD path's M + S - 1 (canonical
    asynchronous 1F1B also needs M + S - 1) — in exchange for O(stages)
    stashed stage inputs instead of GPipe's O(n_micro) activations.
    Efficiency numbers printed from this function reflect that larger
    bubble; pick 1F1B for memory, GPipe for the smaller tick grid."""
    if schedule == "1f1b":
        return (2 * n_stages - 2) / (n_micro + 2 * n_stages - 2)
    return (n_stages - 1) / (n_micro + n_stages - 1)


class LayerDesc:
    """reference: fleet.meta_parallel LayerDesc."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *args, forward_func=None, shared_weight_attr=None,
                 **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key


class PipelineLayer:
    """API-parity container (reference: fleet.meta_parallel.PipelineLayer):
    splits a LayerDesc list into pp stages.

    When constructed with a mesh whose pp axis == num_stages, forward()
    actually executes stage-parallel: the longest homogeneous run of
    layers (same class, same param shapes) is stacked and run through
    pipeline_apply over the mesh, with any heterogeneous head/tail
    layers running replicated outside the pp loop. This is the
    compiled-functional path (params are read out of the layers as raw
    arrays), matching how the reference's PP engine drives the layer —
    not the eager-tape path. Without a mesh, forward is sequential.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, mesh=None,
                 pp_axis="pp", n_micro=None, **kwargs):
        self.descs = layers
        self.num_stages = num_stages or 1
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.pp_axis = pp_axis
        self.n_micro = n_micro
        self.built = [d.build() if isinstance(d, LayerDesc) else d
                      for d in layers]
        self._block = (self._find_homogeneous_block()
                       if self.num_stages > 1 else None)
        self._pipeline_fn = None

    def _find_homogeneous_block(self):
        """[start, end) of the longest run of same-class layers with
        identical param signatures, trimmed to a multiple of num_stages;
        None when no run can fill every stage."""
        sigs = []
        for l in self.built:
            if hasattr(l, "functional_state"):
                p, b = l.functional_state()
                # buffered layers (e.g. BatchNorm) are NOT stackable:
                # functional_call would run every stacked layer with the
                # template's buffer values and silently diverge
                sigs.append(None if b else
                            (type(l),
                             tuple(sorted((n, tuple(a.shape), str(a.dtype))
                                          for n, a in p.items()))))
            else:
                sigs.append(None)
        best = (0, 0)
        i, n = 0, len(sigs)
        while i < n:
            if sigs[i] is None:
                i += 1
                continue
            j = i
            while j < n and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = j
        start, end = best
        count = (end - start) // self.num_stages * self.num_stages
        if count < self.num_stages or count < 2:
            return None
        return (start, start + count)

    def _staged_pipeline(self):
        """Jitted pipeline over the homogeneous block, built once —
        rebuilding per forward would retrace/recompile every step."""
        if self._pipeline_fn is None:
            template = self.built[self._block[0]]

            def layer_fn(lp, h, extra):
                return template.functional_call(lp, {}, h)

            # under jit: shard_map with partial-manual axes (pp manual,
            # the mesh's other axes auto) only composes with GSPMD
            # inside a traced computation; eager would reject them
            self._pipeline_fn = jax.jit(functools.partial(
                pipeline_apply, layer_fn=layer_fn, mesh=self.mesh,
                pp_axis=self.pp_axis, n_micro=self.n_micro))
        return self._pipeline_fn

    def _staged_forward(self, x):
        start, end = self._block
        for l in self.built[:start]:
            x = l(x)
        plist = [l.functional_state()[0] for l in self.built[start:end]]
        stacked = {k: jnp.stack([p[k] for p in plist]) for k in plist[0]}
        raw = x._value if hasattr(x, "_value") else jnp.asarray(x)
        out = self._staged_pipeline()(group_stages(stacked, self.num_stages),
                                      raw)
        for l in self.built[end:]:
            out = l(out)
        return out

    def forward(self, x):
        if (self._block is not None and self.mesh is not None
                and self.mesh.shape.get(self.pp_axis, 1) == self.num_stages):
            return self._staged_forward(x)
        for l in self.built:
            x = l(x)
        return x

    def __call__(self, x):
        return self.forward(x)
