"""Pipeline parallelism (reference: python/paddle/distributed/fleet/
meta_parallel/pipeline_parallel.py — GPipe/1F1B over NCCL p2p).

TPU-native: the pipeline is ONE differentiable SPMD program —
shard_map over the 'pp' mesh axis, lax.scan over microbatch ticks,
lax.ppermute moving activations around the ICI ring. JAX reverse-mode AD
through ppermute/scan yields the backward pipeline automatically (no
hand-written 1F1B schedule or send/recv state machine). Other mesh axes
(dp/tp/sp) remain GSPMD-auto inside each stage.

Requires homogeneous stages: per-layer params stacked on a leading axis,
grouped (n_stages, layers_per_stage, ...).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from .._core.compat import shard_map


def stack_layer_params(layer_params_list):
    """[{name: array} per layer] → {name: array stacked on axis 0}."""
    keys = layer_params_list[0].keys()
    return {k: jnp.stack([lp[k] for lp in layer_params_list]) for k in keys}


def group_stages(stacked, n_stages):
    """{name: (L, ...)} → {name: (n_stages, L/n_stages, ...)}."""
    def regroup(a):
        L = a.shape[0]
        assert L % n_stages == 0, f"layers {L} not divisible by pp={n_stages}"
        return a.reshape(n_stages, L // n_stages, *a.shape[1:])
    return jax.tree_util.tree_map(regroup, stacked)


def _f32z(tree):
    return jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, jnp.float32), tree)


def _head_vjp(head_fn, head_p, y, tgt):
    """Head vjp for the hand-seeded schedules: head_fn returns
    (loss_sum, weight); backward is seeded with d/d(loss_sum)=1 and the
    global 1/Σweight normalization is applied once in _epilogue."""
    loss_m, pull, w_m = jax.vjp(
        lambda hp, yy: head_fn(hp, yy, tgt), head_p, y, has_aux=True)
    ghp, gy = pull(jnp.float32(1.0))
    return (loss_m, jnp.float32(w_m),
            jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), ghp),
            gy.astype(y.dtype))


def _stage_vjp(fn, params, inp, gin):
    """Backward of one stage/chunk forward, recomputing the forward
    from the stashed input (remat); grads cast to fp32 for
    accumulation, activation grad kept in the ring dtype."""
    _, pull = jax.vjp(fn, params, inp)
    gp, gh = pull(gin)
    return (jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), gp),
            gh.astype(gin.dtype))


def _epilogue(r, S, pp_axis, gparams, ghead, dx, losses, wts):
    """Shared normalization: replicate losses/weights from the last
    rank and dx from rank 0, then scale every gradient by the GLOBAL
    1/Σweight (valid-token count for NLL heads)."""
    is_last = r == S - 1
    losses = lax.psum(jnp.where(is_last, losses, jnp.zeros_like(losses)),
                      pp_axis)
    wts = lax.psum(jnp.where(is_last, wts, jnp.zeros_like(wts)), pp_axis)
    inv_w = 1.0 / jnp.maximum(jnp.sum(wts), 1e-9)
    gparams = jax.tree_util.tree_map(
        lambda a: (a * inv_w)[None], gparams)  # re-add the stage axis
    ghead = jax.tree_util.tree_map(
        lambda a: lax.psum(a, pp_axis) * inv_w, ghead)
    dx = lax.psum(jnp.where(r == 0, dx, jnp.zeros_like(dx)),
                  pp_axis) * inv_w
    return gparams, ghead, dx, losses, wts


def pipeline_apply(stage_params, x, layer_fn, mesh, pp_axis="pp", n_micro=None,
                   extra=None):
    """Differentiable GPipe forward.

    stage_params: pytree, leaves (n_stages, layers_per_stage, ...) —
      sharded over pp on axis 0.
    x: (B, ...) activations entering stage 0 (replicated over pp).
    layer_fn(layer_params, h, extra) → h : one transformer layer.
    extra: static per-call aux (e.g. rope tables), replicated.
    Returns activations after the last stage, replicated over pp.
    """
    n_stages = mesh.shape[pp_axis]
    B = x.shape[0]
    if n_micro is None:
        n_micro = n_stages
    assert B % n_micro == 0, f"batch {B} not divisible by n_micro {n_micro}"
    mb = B // n_micro
    x_micro = x.reshape(n_micro, mb, *x.shape[1:])

    def stage_fn(params_local, h, extra_):
        # params_local leaves: (layers_per_stage, ...) → scan over layers
        def body(carry, layer_params):
            return layer_fn(layer_params, carry, extra_), None
        out, _ = lax.scan(body, h, params_local)
        return out

    def per_rank(params_shard, xm, extra_):
        # params_shard leaves: (1, layers_per_stage, ...) local shard
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_shard)
        idx = lax.axis_index(pp_axis)
        total = n_micro + n_stages - 1
        buf0 = jnp.zeros_like(xm[0])
        out0 = jnp.zeros_like(xm)

        def tick(carry, t):
            buf, outs = carry
            inp = jnp.where(idx == 0,
                            xm[jnp.clip(t, 0, n_micro - 1)], buf)
            # idle-tick skip: stage `idx` only has real work while
            # 0 <= t - idx < n_micro; outside that window the cond's
            # passthrough branch costs nothing instead of computing
            # garbage (VERDICT r2 weak #4: was up to 1.5x wasted FLOPs)
            active = ((t - idx) >= 0) & ((t - idx) < n_micro)
            y = lax.cond(active,
                         lambda h: stage_fn(params_local, h, extra_),
                         lambda h: h, inp)
            m = t - (n_stages - 1)
            write = (idx == n_stages - 1) & (m >= 0)
            outs = lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(write, y, lax.dynamic_index_in_dim(
                    outs, jnp.clip(m, 0, n_micro - 1), 0, keepdims=False)),
                jnp.clip(m, 0, n_micro - 1), 0)
            nxt = lax.ppermute(y, pp_axis,
                               [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (nxt, outs), None

        (_, outs), _ = lax.scan(tick, (buf0, out0), jnp.arange(total))
        # replicate result from the last stage to all pp ranks
        outs = lax.psum(jnp.where(idx == n_stages - 1, outs,
                                  jnp.zeros_like(outs)), pp_axis)
        return outs

    mapped = shard_map(
        per_rank, mesh=mesh,
        in_specs=(P(pp_axis), P(), P()),
        out_specs=P(),
        axis_names=frozenset({pp_axis}),
        check_vma=False)
    out = mapped(stage_params, x_micro, extra if extra is not None else jnp.zeros(()))
    return out.reshape(B, *out.shape[2:])


def pipeline_train_1f1b(stage_params, x, targets, layer_fn, head_fn,
                        head_params, mesh, pp_axis="pp", n_micro=None,
                        extra=None):
    """One-forward-one-backward (PipeDream-flush) pipeline TRAIN pass.

    Reference schedule: python/paddle/distributed/fleet/meta_parallel/
    pipeline_parallel.py:958 (1F1B over NCCL p2p). TPU-native: ONE
    lax.scan over global ticks inside shard_map; each tick runs a
    forward sub-tick and a backward sub-tick, with activations moving
    forward and gradients moving backward over the ICI ring in the same
    step. The backward is hand-seeded (loss computed in-pipeline on the
    last stage via `head_fn`), so only a ring of 2*n_stages stage
    INPUTS is ever stashed — the defining 1F1B property of O(stages)
    activation memory instead of GPipe's O(n_micro) — and each stage's
    backward recomputes its forward from the stashed input (remat).

    Timing: stage s forwards microbatch m at tick t = m + s and
    backwards it at t = m + 2S - 2 - s, so the last stage does fwd(m)
    and bwd(m) in the SAME tick (its head-vjp seeds the backward), and
    every other stage receives the gradient one tick after its
    downstream neighbour produced it. Total ticks = M + 2S - 2; the
    steady state is exactly one forward + one backward per tick.

    Args:
      stage_params: pytree, leaves (n_stages, layers_per_stage, ...),
        sharded over pp on axis 0.
      x: (B, ...) activations entering stage 0 (replicated over pp).
      targets: (B, ...) labels, consumed by head_fn on the last stage.
      layer_fn(layer_params, h, extra) -> h: one transformer layer.
      head_fn(head_params, h, targets_mb) -> (loss_sum, weight) for one
        microbatch (fold final-norm + lm_head + loss here). The
        pipeline's loss is sum(loss_sum) / sum(weight) over all
        microbatches, so with ignore-labels every microbatch is
        weighted by its VALID token count — exactly matching the no-pp
        and grad-accum paths even with unevenly distributed masking.
        For plain mean-loss semantics return (mean_loss, 1.0).
      head_params: pytree, replicated.
    Returns:
      (loss, stage_grads, head_grads, dx) — loss = Σ loss_sum / Σ
      weight; stage_grads matches stage_params' structure/sharding
      (fp32), head_grads matches head_params (fp32, replicated), dx is
      dLoss/dx (B, ...).
    """
    n_stages = mesh.shape[pp_axis]
    B = x.shape[0]
    if n_micro is None:
        n_micro = n_stages
    assert B % n_micro == 0, f"batch {B} not divisible by n_micro {n_micro}"
    mb = B // n_micro
    M, S = n_micro, n_stages
    x_micro = x.reshape(M, mb, *x.shape[1:])
    t_micro = targets.reshape(M, mb, *targets.shape[1:])
    cap = 2 * S  # in-flight stage inputs are consecutive and <= 2S-1
    total = M + 2 * S - 2

    def stage_fn(params_local, h, extra_):
        def body(carry, layer_params):
            return layer_fn(layer_params, carry, extra_), None
        out, _ = lax.scan(body, h, params_local)
        return out

    def per_rank(params_shard, xm, tm, head_p, extra_):
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_shard)
        s = lax.axis_index(pp_axis)
        is_last = s == S - 1

        stash0 = jnp.zeros((cap,) + xm.shape[1:], xm.dtype)
        act0 = jnp.zeros_like(xm[0])
        carry0 = (stash0, act0, act0, _f32z(params_local), _f32z(head_p),
                  jnp.zeros_like(xm), jnp.zeros((M,), jnp.float32),
                  jnp.zeros((M,), jnp.float32))

        def tick(carry, t):
            stash, fwd_buf, bwd_buf, gparams, ghead, dx, losses, wts = carry

            # ---- forward sub-tick: microbatch mf = t - s
            mf = t - s
            f_active = (mf >= 0) & (mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            inp = jnp.where(s == 0, xm[mf_c], fwd_buf)
            y = lax.cond(f_active,
                         lambda h: stage_fn(params_local, h, extra_),
                         lambda h: h, inp)
            stash = lax.cond(
                f_active,
                lambda st: lax.dynamic_update_index_in_dim(
                    st, inp, mf_c % cap, 0),
                lambda st: st, stash)

            # last stage: head vjp NOW — its gy seeds this tick's
            # backward sub-tick (bwd microbatch == mf on the last stage)
            loss_m, w_m, ghp, gy = lax.cond(
                f_active & is_last,
                lambda args: _head_vjp(head_fn, head_p, *args),
                lambda args: (jnp.float32(0.0), jnp.float32(0.0),
                              _f32z(head_p), jnp.zeros_like(args[0])),
                (y, tm[mf_c]))
            ghead = jax.tree_util.tree_map(lambda a, b: a + b, ghead, ghp)
            losses = lax.cond(
                f_active & is_last,
                lambda ls: ls.at[mf_c].set(loss_m),
                lambda ls: ls, losses)
            wts = lax.cond(
                f_active & is_last,
                lambda ws: ws.at[mf_c].set(w_m),
                lambda ws: ws, wts)

            # ---- backward sub-tick: microbatch mb_ = t - (2S - 2 - s)
            mb_ = t - (2 * S - 2 - s)
            b_active = (mb_ >= 0) & (mb_ < M)
            mb_c = jnp.clip(mb_, 0, M - 1)
            inp_b = lax.dynamic_index_in_dim(stash, mb_c % cap, 0,
                                             keepdims=False)
            gin = jnp.where(is_last, gy, bwd_buf)

            gp, gh = lax.cond(
                b_active,
                lambda args: _stage_vjp(
                    lambda p, h: stage_fn(p, h, extra_), params_local,
                    *args),
                lambda args: (_f32z(params_local),
                              jnp.zeros_like(args[1])),
                (inp_b, gin))
            gparams = jax.tree_util.tree_map(lambda a, b: a + b, gparams, gp)
            dx = lax.cond(
                b_active & (s == 0),
                lambda d: lax.dynamic_update_index_in_dim(
                    d, gh.astype(d.dtype), mb_c, 0),
                lambda d: d, dx)

            # ---- ring hops (uniform across ranks — never inside cond)
            fwd_buf = lax.ppermute(
                y, pp_axis, [(i, (i + 1) % S) for i in range(S)])
            bwd_buf = lax.ppermute(
                gh, pp_axis, [(i, (i - 1) % S) for i in range(S)])
            return (stash, fwd_buf, bwd_buf, gparams, ghead, dx,
                    losses, wts), None

        (_, _, _, gparams, ghead, dx, losses, wts), _ = lax.scan(
            tick, carry0, jnp.arange(total))
        return _epilogue(s, S, pp_axis, gparams, ghead, dx, losses, wts)

    mapped = shard_map(
        per_rank, mesh=mesh,
        in_specs=(P(pp_axis), P(), P(), P(), P()),
        out_specs=(P(pp_axis), P(), P(), P(), P()),
        axis_names=frozenset({pp_axis}),
        check_vma=False)
    gstage, ghead, dx, losses, wts = mapped(
        stage_params, x_micro, t_micro, head_params,
        extra if extra is not None else jnp.zeros(()))
    loss = jnp.sum(losses) / jnp.maximum(jnp.sum(wts), 1e-9)
    return loss, gstage, ghead, dx.reshape(B, *dx.shape[2:])


def group_virtual_stages(stacked, n_stages, vpp):
    """{name: (L, ...)} → {name: (n_stages, vpp, L/(S*v), ...)} laid out
    for the interleaved schedule: virtual stage j = c*S + r (chunk c of
    rank r) owns the j-th contiguous run of layers — rank r holds
    chunks r, r+S, ..., r+(v-1)S of the model (Megatron vpp layout)."""
    Sv = n_stages * vpp
    perm = np.arange(vpp)[None, :] * n_stages + np.arange(n_stages)[:, None]

    def regroup(a):
        L = a.shape[0]
        assert L % Sv == 0, \
            f"layers {L} not divisible by pp*vpp={n_stages}*{vpp}"
        chunks = a.reshape(Sv, L // Sv, *a.shape[1:])
        return chunks[perm]  # (S, v, Lc, ...)

    return jax.tree_util.tree_map(regroup, stacked)


def ungroup_virtual_stages(grouped, n_stages, vpp):
    """Inverse of group_virtual_stages: (S, v, Lc, ...) → (L, ...)."""
    inv = np.argsort(
        (np.arange(vpp)[None, :] * n_stages
         + np.arange(n_stages)[:, None]).reshape(-1))

    def flatten(a):
        Sv = n_stages * vpp
        flat = a.reshape(Sv, *a.shape[2:])
        return flat[inv].reshape(Sv * a.shape[2], *a.shape[3:])

    return jax.tree_util.tree_map(flatten, grouped)


def build_interleaved_schedule(n_micro, n_stages, vpp):
    """Static lockstep slot tables for interleaved (virtual-stage) 1F1B.

    Greedy list scheduling under the lockstep constraints — per tick
    each rank runs at most one chunk-forward and one chunk-backward,
    and activations/gradients hop exactly one rank per tick (ppermute)
    with arrival the next tick. Forward priority is deepest-virtual-
    stage-first (drives the first microbatches to the head ASAP);
    backward is FIFO by microbatch. The resulting wall-clock matches
    Megatron's interleaved 1F1B: fill/drain cost (S-1)/v stage-units
    (reference pipeline_parallel.py:1309, :1359-1367).

    Returns a dict of int32 numpy tables, each (T, S):
      f_c/f_m:   chunk/microbatch of the forward slot (-1 = idle)
      b_c/b_m:   same for the backward slot
      rf_c/rf_m: chunk/mb of the activation arriving at tick start
                 (produced by rank r-1 last tick) to stash (-1 = none)
      rb_c/rb_m: same for the arriving gradient (from rank r+1)
    plus scalars T, in_cap, g_cap (stash depths, collision-free mod-cap
    indexing proven against the schedule itself).
    """
    M, S, v = n_micro, n_stages, vpp
    Sv = S * v
    INF = 1 << 30
    avail_f = {(j, m): (0 if j == 0 else INF)
               for j in range(Sv) for m in range(M)}
    avail_b = {(j, m): INF for j in range(Sv) for m in range(M)}
    done_f, done_b = set(), set()
    slots = {r: [] for r in range(S)}
    arrive_f = {}   # (j, m) -> tick its input lands in the stash
    arrive_g = {}   # (j, m) -> tick its upstream grad lands
    bwd_at = {}
    t = 0
    while len(done_b) < Sv * M:
        assert t < 4 * (M + 2 * Sv), "interleave scheduler wedged"
        produced = []
        for r in range(S):
            js = [c * S + r for c in range(v)]
            cand_f = [(j, m) for j in js for m in range(M)
                      if (j, m) not in done_f and avail_f[(j, m)] <= t]
            f_op = min(cand_f, key=lambda jm: (-jm[0], jm[1])) \
                if cand_f else None
            cand_b = [(j, m) for j in js for m in range(M)
                      if (j, m) not in done_b and avail_b[(j, m)] <= t]
            if f_op and f_op[0] == Sv - 1:
                cand_b.append(f_op)  # head seeds its own bwd this tick
            b_op = min(cand_b, key=lambda jm: (jm[1], -jm[0])) \
                if cand_b else None
            slots[r].append((f_op, b_op))
            produced.append((r, f_op, b_op))
        for r, f_op, b_op in produced:
            if f_op:
                done_f.add(f_op)
                j, m = f_op
                if j + 1 < Sv:
                    avail_f[(j + 1, m)] = t + 1
                    arrive_f[(j + 1, m)] = t + 1
                else:
                    avail_b[(j, m)] = min(avail_b[(j, m)], t)
                    arrive_g[(j, m)] = t  # head gy written same tick
            if b_op:
                done_b.add(b_op)
                bwd_at[b_op] = t
                j, m = b_op
                if j - 1 >= 0:
                    avail_b[(j - 1, m)] = t + 1
                    arrive_g[(j - 1, m)] = t + 1
        t += 1
    T = t

    tabs = {k: np.full((T, S), -1, np.int32)
            for k in ("f_c", "f_m", "b_c", "b_m",
                      "rf_c", "rf_m", "rb_c", "rb_m")}
    for r in range(S):
        for t_, (f_op, b_op) in enumerate(slots[r]):
            if f_op:
                tabs["f_c"][t_, r] = f_op[0] // S
                tabs["f_m"][t_, r] = f_op[1]
            if b_op:
                tabs["b_c"][t_, r] = b_op[0] // S
                tabs["b_m"][t_, r] = b_op[1]
    # receive tables: what rank r must stash at the START of tick t is
    # whatever its ring neighbour produced at t-1
    for r in range(S):
        p = (r - 1) % S
        for t_ in range(1, T):
            fp, _ = slots[p][t_ - 1]
            if fp and fp[0] + 1 < Sv and (fp[0] + 1) % S == r:
                tabs["rf_c"][t_, r] = (fp[0] + 1) // S
                tabs["rf_m"][t_, r] = fp[1]
        p = (r + 1) % S
        for t_ in range(1, T):
            _, bp = slots[p][t_ - 1]
            if bp and bp[0] - 1 >= 0 and (bp[0] - 1) % S == r:
                tabs["rb_c"][t_, r] = (bp[0] - 1) // S
                tabs["rb_m"][t_, r] = bp[1]

    def min_cap(arrive, release):
        """Smallest cap with no mod-cap collision: for every pair of
        same-chunk ops m < m', m' must not land on m's slot while m is
        live (live = [arrive, release])."""
        for cap in range(1, M + 1):
            ok = True
            for (j, m), a in arrive.items():
                rel = release.get((j, m), a)
                m2 = m + cap
                while ok and (j, m2) in arrive:
                    if arrive[(j, m2)] <= rel:
                        ok = False
                    m2 += cap
                if not ok:
                    break
            if ok:
                return cap
        return M

    # forward-input stash entries live from arrival until the chunk's
    # backward consumes them for recompute; grad entries from arrival
    # until the backward runs
    in_cap = min_cap(arrive_f, bwd_at)
    g_cap = min_cap(arrive_g, bwd_at)
    return dict(tabs, T=T, in_cap=max(in_cap, 1), g_cap=max(g_cap, 1))


def pipeline_train_interleaved(stage_params, x, targets, layer_fn, head_fn,
                               head_params, mesh, pp_axis="pp", n_micro=None,
                               vpp=2, extra=None):
    """Interleaved virtual-stage 1F1B TRAIN pass (Megatron vpp parity;
    reference python/paddle/distributed/fleet/meta_parallel/
    pipeline_parallel.py:1309 — ours is a single lockstep lax.scan
    driven by the static slot tables from build_interleaved_schedule).

    Each physical stage owns vpp non-adjacent layer chunks (stage r
    holds chunks r, r+S, ..., virtual stage j = c*S + r), so the
    pipeline fill/drain costs (S-1)/vpp stage-units instead of (S-1) —
    the standard bubble lever once 1F1B works. Backward recomputes each
    chunk forward from its stashed input (same remat policy as
    pipeline_train_1f1b).

    Args as pipeline_train_1f1b, except stage_params leaves are
    (n_stages, vpp, layers_per_chunk, ...) — see group_virtual_stages —
    and head_fn keeps the (loss_sum, weight) contract.
    Returns (loss, stage_grads, head_grads, dx) with stage_grads
    matching stage_params' layout.
    """
    n_stages = mesh.shape[pp_axis]
    B = x.shape[0]
    if n_micro is None:
        n_micro = n_stages * vpp
    assert B % n_micro == 0, f"batch {B} not divisible by n_micro {n_micro}"
    mb = B // n_micro
    M, S, v = n_micro, n_stages, vpp
    sched = build_interleaved_schedule(M, S, v)
    T, in_cap, g_cap = sched["T"], sched["in_cap"], sched["g_cap"]
    tables = jnp.stack([jnp.asarray(sched[k]) for k in
                        ("f_c", "f_m", "b_c", "b_m",
                         "rf_c", "rf_m", "rb_c", "rb_m")], axis=1)  # (T,8,S)
    x_micro = x.reshape(M, mb, *x.shape[1:])
    t_micro = targets.reshape(M, mb, *targets.shape[1:])

    def chunk_fn(params_chunk, h, extra_):
        def body(carry, layer_params):
            return layer_fn(layer_params, carry, extra_), None
        out, _ = lax.scan(body, h, params_chunk)
        return out

    def per_rank(params_shard, xm, tm, head_p, extra_, tabs):
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_shard)
        r = lax.axis_index(pp_axis)

        mb_shape = xm.shape[1:]
        in_stash0 = jnp.zeros((v, in_cap) + mb_shape, xm.dtype)
        g_stash0 = jnp.zeros((v, g_cap) + mb_shape, xm.dtype)
        act0 = jnp.zeros_like(xm[0])
        carry0 = (in_stash0, g_stash0, act0, act0, _f32z(params_local),
                  _f32z(head_p), jnp.zeros_like(xm),
                  jnp.zeros((M,), jnp.float32), jnp.zeros((M,), jnp.float32))

        def pick(params, c):
            return jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                params)

        def tick(carry, row):
            (in_stash, g_stash, fwd_in, bwd_in, gparams, ghead, dx,
             losses, wts) = carry
            f_c, f_m, b_c, b_m, rf_c, rf_m, rb_c, rb_m = [
                jnp.take(row[i], r) for i in range(8)]

            # ---- 0. stash what the ring delivered at end of last tick
            in_stash = lax.cond(
                rf_c >= 0,
                lambda st: lax.dynamic_update_index_in_dim(
                    st, lax.dynamic_update_index_in_dim(
                        lax.dynamic_index_in_dim(
                            st, jnp.clip(rf_c, 0, v - 1), 0, keepdims=False),
                        fwd_in, jnp.clip(rf_m, 0, M - 1) % in_cap, 0),
                    jnp.clip(rf_c, 0, v - 1), 0),
                lambda st: st, in_stash)
            g_stash = lax.cond(
                rb_c >= 0,
                lambda st: lax.dynamic_update_index_in_dim(
                    st, lax.dynamic_update_index_in_dim(
                        lax.dynamic_index_in_dim(
                            st, jnp.clip(rb_c, 0, v - 1), 0, keepdims=False),
                        bwd_in, jnp.clip(rb_m, 0, M - 1) % g_cap, 0),
                    jnp.clip(rb_c, 0, v - 1), 0),
                lambda st: st, g_stash)

            # ---- 1. forward sub-tick
            f_active = f_c >= 0
            fc = jnp.clip(f_c, 0, v - 1)
            fm = jnp.clip(f_m, 0, M - 1)
            from_input = (r == 0) & (fc == 0)
            stashed = lax.dynamic_index_in_dim(
                lax.dynamic_index_in_dim(in_stash, fc, 0, keepdims=False),
                fm % in_cap, 0, keepdims=False)
            inp = jnp.where(from_input, xm[fm], stashed)
            y = lax.cond(f_active,
                         lambda h: chunk_fn(pick(params_local, fc), h,
                                            extra_),
                         lambda h: h, inp)

            # head: last virtual stage (chunk v-1 on rank S-1)
            is_head = f_active & (r == S - 1) & (fc == v - 1)
            loss_m, w_m, ghp, gy = lax.cond(
                is_head,
                lambda args: _head_vjp(head_fn, head_p, *args),
                lambda args: (jnp.float32(0.0), jnp.float32(0.0),
                              _f32z(head_p), jnp.zeros_like(args[0])),
                (y, tm[fm]))
            ghead = jax.tree_util.tree_map(lambda a, b: a + b, ghead, ghp)
            losses = lax.cond(is_head, lambda ls: ls.at[fm].set(loss_m),
                              lambda ls: ls, losses)
            wts = lax.cond(is_head, lambda ws: ws.at[fm].set(w_m),
                           lambda ws: ws, wts)
            # the head's gy enters the grad stash like any arrival
            g_stash = lax.cond(
                is_head,
                lambda st: lax.dynamic_update_index_in_dim(
                    st, lax.dynamic_update_index_in_dim(
                        lax.dynamic_index_in_dim(st, v - 1, 0,
                                                 keepdims=False),
                        gy, fm % g_cap, 0),
                    v - 1, 0),
                lambda st: st, g_stash)

            # ---- 2. backward sub-tick (recomputes the chunk forward)
            b_active = b_c >= 0
            bc = jnp.clip(b_c, 0, v - 1)
            bm = jnp.clip(b_m, 0, M - 1)
            b_from_input = (r == 0) & (bc == 0)
            inp_b = jnp.where(
                b_from_input, xm[bm],
                lax.dynamic_index_in_dim(
                    lax.dynamic_index_in_dim(in_stash, bc, 0,
                                             keepdims=False),
                    bm % in_cap, 0, keepdims=False))
            gin = lax.dynamic_index_in_dim(
                lax.dynamic_index_in_dim(g_stash, bc, 0, keepdims=False),
                bm % g_cap, 0, keepdims=False)

            gp, gh = lax.cond(
                b_active,
                lambda args: _stage_vjp(
                    lambda p, h: chunk_fn(p, h, extra_),
                    pick(params_local, bc), *args),
                lambda args: (_f32z(pick(params_local, 0)),
                              jnp.zeros_like(args[1])),
                (inp_b, gin))
            # scatter-add this chunk's grads into the (v, ...) slab;
            # inactive ticks add zeros to chunk 0 (harmless)
            gparams = jax.tree_util.tree_map(
                lambda G, g: G.at[bc].add(g), gparams, gp)
            dx = lax.cond(
                b_active & b_from_input,
                lambda d: lax.dynamic_update_index_in_dim(
                    d, gh.astype(d.dtype), bm, 0),
                lambda d: d, dx)

            # ---- 3. ring hops (uniform across ranks)
            fwd_in = lax.ppermute(
                y, pp_axis, [(i, (i + 1) % S) for i in range(S)])
            bwd_in = lax.ppermute(
                gh, pp_axis, [(i, (i - 1) % S) for i in range(S)])
            return (in_stash, g_stash, fwd_in, bwd_in, gparams, ghead,
                    dx, losses, wts), None

        (_, _, _, _, gparams, ghead, dx, losses, wts), _ = lax.scan(
            tick, carry0, tabs)
        return _epilogue(r, S, pp_axis, gparams, ghead, dx, losses, wts)

    mapped = shard_map(
        per_rank, mesh=mesh,
        in_specs=(P(pp_axis), P(), P(), P(), P(), P()),
        out_specs=(P(pp_axis), P(), P(), P(), P()),
        axis_names=frozenset({pp_axis}),
        check_vma=False)
    gstage, ghead, dx, losses, wts = mapped(
        stage_params, x_micro, t_micro, head_params,
        extra if extra is not None else jnp.zeros(()), tables)
    loss = jnp.sum(losses) / jnp.maximum(jnp.sum(wts), 1e-9)
    return loss, gstage, ghead, dx.reshape(B, *dx.shape[2:])


def pipeline_bubble_fraction(n_micro, n_stages, schedule="1f1b", vpp=1):
    """Wall-clock idle fraction of the pipeline.

    All our schedules run on a lockstep tick grid (longer than the
    canonical asynchronous schedules' slot count), but inactive
    sub-ticks are lax.cond passthroughs costing ~nothing, so the
    wall-clock bubble matches the canonical formulas (verified by
    per-tick cost simulation, tests/test_interleave_pp.py):

      gpipe / 1f1b:  (S-1) / (M + S-1)       — same wall clock; 1F1B's
                     win is O(stages) stashed inputs vs O(n_micro)
                     activations, paid for with fwd recompute in bwd.
      interleave:    ((S-1)/v) / (M + (S-1)/v) — v virtual chunks per
                     stage divide the fill/drain cost by v (Megatron
                     interleaved 1F1B parity, reference
                     pipeline_parallel.py:1309).
    """
    M, S = n_micro, n_stages
    if schedule == "interleave":
        assert vpp > 1, ("interleave bubble needs the vpp actually used "
                         "(vpp=1 would silently report the plain 1F1B "
                         "bubble)")
        fill = (S - 1) / vpp
    else:
        fill = S - 1
    return fill / (M + fill)


class LayerDesc:
    """reference: fleet.meta_parallel LayerDesc."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *args, forward_func=None, shared_weight_attr=None,
                 **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key


class PipelineLayer:
    """API-parity container (reference: fleet.meta_parallel.PipelineLayer):
    splits a LayerDesc list into pp stages.

    When constructed with a mesh whose pp axis == num_stages, forward()
    actually executes stage-parallel: EVERY maximal homogeneous run of
    layers (same class, same param shapes) long enough to fill the
    stages is stacked and run through pipeline_apply over the mesh —
    arbitrary LayerDesc lists (embed → blocksA → blocksB → head) stage
    each run, with the heterogeneous layers between runs executing
    replicated (reference seg-method parity: the reference segments any
    LayerDesc list; ours stages the stackable runs and warns when
    nothing is stackable). This is the compiled-functional path (params
    are read out of the layers as raw arrays), matching how the
    reference's PP engine drives the layer — not the eager-tape path.
    Without a mesh, forward is sequential.

    seg_method: "uniform" (default) stages every eligible run;
    "layer:ClassName" stages only runs of that class (reference
    seg_method="layer:..." cut-point parity). recompute_interval > 0
    wraps each staged layer in jax.checkpoint (activation remat inside
    the pipeline, reference recompute_interval semantics at
    granularity 1).
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, mesh=None,
                 pp_axis="pp", n_micro=None, **kwargs):
        import warnings
        self.descs = layers
        self.num_stages = num_stages or 1
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.pp_axis = pp_axis
        self.n_micro = n_micro
        self.seg_method = seg_method
        self.recompute_interval = int(recompute_interval)
        if not (seg_method == "uniform"
                or str(seg_method).startswith("layer:")):
            raise ValueError(
                f"seg_method={seg_method!r} unsupported: use 'uniform' "
                "or 'layer:ClassName'")
        self.built = [d.build() if isinstance(d, LayerDesc) else d
                      for d in layers]
        self._segments = (self._find_stageable_segments()
                          if self.num_stages > 1 else [])
        self._pipeline_fns = {}
        if self.num_stages > 1 and self.mesh is not None:
            mesh_pp = self.mesh.shape.get(self.pp_axis, 1)
            if not self._segments:
                warnings.warn(
                    f"PipelineLayer(num_stages={self.num_stages}): no "
                    f"homogeneous run of >= {self.num_stages} stackable "
                    "layers found — forward() will run SEQUENTIALLY "
                    "(replicated), not pipelined. Stage-parallel "
                    "execution needs same-class layers with identical "
                    f"param shapes (seg_method={seg_method!r}).",
                    stacklevel=2)
            elif mesh_pp != self.num_stages:
                warnings.warn(
                    f"PipelineLayer(num_stages={self.num_stages}): mesh "
                    f"'{self.pp_axis}' axis has {mesh_pp} devices — "
                    "forward() will run SEQUENTIALLY (replicated), not "
                    "pipelined. Make num_stages match the mesh's pp "
                    "axis.", stacklevel=2)
        if self.recompute_interval > 0 and not self._will_stage():
            warnings.warn(
                f"PipelineLayer: recompute_interval="
                f"{self.recompute_interval} only applies on the staged "
                "pipeline path; this construction runs sequentially "
                "(no mesh / mesh-axis mismatch / nothing stackable), so "
                "NO activation recompute will happen.", stacklevel=2)

    def _will_stage(self):
        """True iff forward() will take the stage-parallel path."""
        return bool(
            self._segments and self.mesh is not None
            and self.mesh.shape.get(self.pp_axis, 1) == self.num_stages)

    def _layer_sig(self, l):
        if not hasattr(l, "functional_state"):
            return None
        p, b = l.functional_state()
        # buffered layers (e.g. BatchNorm) are NOT stackable:
        # functional_call would run every stacked layer with the
        # template's buffer values and silently diverge
        if b:
            return None
        sig = (type(l), tuple(sorted((n, tuple(a.shape), str(a.dtype))
                                     for n, a in p.items())))
        if str(self.seg_method).startswith("layer:"):
            want = str(self.seg_method)[len("layer:"):]
            if type(l).__name__ != want:
                return None
        return sig

    def _find_stageable_segments(self):
        """All maximal runs of same-signature layers, each trimmed to
        the largest multiple of num_stages (leftover tail layers run
        sequentially); empty when nothing can fill every stage."""
        sigs = [self._layer_sig(l) for l in self.built]
        segments = []
        i, n = 0, len(sigs)
        while i < n:
            if sigs[i] is None:
                i += 1
                continue
            j = i
            while j < n and sigs[j] == sigs[i]:
                j += 1
            count = (j - i) // self.num_stages * self.num_stages
            if count >= self.num_stages and count >= 2:
                segments.append((i, i + count))
            i = j
        return segments

    def _staged_pipeline(self, seg):
        """Jitted pipeline per staged segment, built once — rebuilding
        per forward would retrace/recompile every step."""
        if seg not in self._pipeline_fns:
            template = self.built[seg[0]]

            def layer_fn(lp, h, extra):
                return template.functional_call(lp, {}, h)
            if self.recompute_interval > 0:
                layer_fn = jax.checkpoint(layer_fn)

            # under jit: shard_map with partial-manual axes (pp manual,
            # the mesh's other axes auto) only composes with GSPMD
            # inside a traced computation; eager would reject them
            from ..observability.compile_telemetry import track_jit
            self._pipeline_fns[seg] = track_jit(
                f"parallel.pipeline_apply:{seg[0]}-{seg[1]}")(
                jax.jit(functools.partial(
                    pipeline_apply, layer_fn=layer_fn, mesh=self.mesh,
                    pp_axis=self.pp_axis, n_micro=self.n_micro)))
        return self._pipeline_fns[seg]

    def _staged_forward(self, x):
        pos = 0
        for start, end in self._segments:
            for l in self.built[pos:start]:
                x = l(x)
            plist = [l.functional_state()[0]
                     for l in self.built[start:end]]
            stacked = stack_layer_params(plist)
            raw = x._value if hasattr(x, "_value") else jnp.asarray(x)
            x = self._staged_pipeline((start, end))(
                group_stages(stacked, self.num_stages), raw)
            pos = end
        for l in self.built[pos:]:
            x = l(x)
        return x

    def forward(self, x):
        if self._will_stage():
            return self._staged_forward(x)
        for l in self.built:
            x = l(x)
        return x

    def __call__(self, x):
        return self.forward(x)
