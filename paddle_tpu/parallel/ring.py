"""Ring attention — context/sequence parallelism for long sequences.

Replaces the reference's segment-parallel path (python/paddle/distributed/
fleet/meta_parallel/segment_parallel.py) with the TPU-native ring:
sequence sharded over the 'sp' mesh axis, K/V blocks rotate around the
ICI ring via lax.ppermute, online-softmax merging keeps O(S_local) memory.
Differentiable end-to-end (AD through ppermute), so the backward is a
reverse ring — no hand-written comm schedule.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from .._core.compat import axis_size, shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask=None):
    """Block scores + unnormalized accumulation pieces.
    q: (B,H,Sq,D), k/v: (B,H,Sk,D) → (m, l, acc) partials."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m, l, acc


def ring_attention_local(q, k, v, axis_name, causal=False, sm_scale=None,
                         q_chunk=None):
    """Runs INSIDE shard_map: q,k,v (B,H,S_local,D) sequence-sharded over
    `axis_name`. Returns (B,H,S_local,D).

    q_chunk bounds the materialized score tile to (chunk, S_local)
    instead of (S_local, S_local) — the long-context memory knob (defaults
    to 512 when S_local exceeds it). The chunk body is jax.checkpoint'd so
    the bound holds under AD too: backward recomputes each chunk's scores
    instead of stacking per-chunk softmax residuals."""
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    d = q.shape[-1]
    s_local = q.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if q_chunk is None:
        q_chunk = 512
    q_chunk = min(q_chunk, s_local)

    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # chunk q ONCE, outside the ring loop (it never changes per step)
    chunked = q_chunk < s_local
    if chunked:
        n_ch = -(-s_local // q_chunk)
        qp = q
        if n_ch * q_chunk != s_local:
            qp = jnp.pad(q, ((0, 0),) * (q.ndim - 2) +
                         ((0, n_ch * q_chunk - s_local), (0, 0)))
        qs = jnp.moveaxis(qp.reshape(*q.shape[:-2], n_ch, q_chunk, d),
                          -3, 0)                     # (n_ch, B, H, C, D)
        row0s = jnp.arange(n_ch) * q_chunk

    def one_chunk(qc, row0, k_rot, v_rot, src):
        if causal:
            rows = row0 + jnp.arange(qc.shape[-2])[:, None]
            cols = jnp.arange(s_local)[None, :]
            diag_mask = rows >= cols
            mask = jnp.where(src == idx, diag_mask, src < idx)
            mask = jnp.broadcast_to(
                mask, qc.shape[:-2] + (qc.shape[-2], s_local))
            return _block_attn(qc, k_rot, v_rot, scale, mask)
        return _block_attn(qc, k_rot, v_rot, scale)

    # checkpoint: backward recomputes the chunk's scores — without this
    # the scan would stack per-chunk softmax residuals and the memory
    # bound would not survive differentiation
    one_chunk_ckpt = jax.checkpoint(one_chunk)

    def block(k_rot, v_rot, t):
        """(m, l, acc) partials of this K/V block, q chunked."""
        src = (idx - t) % n  # which shard's K/V we currently hold
        if not chunked:
            return one_chunk(q, 0, k_rot, v_rot, src)

        def scan_chunk(_, xs):
            qc, r0 = xs
            return None, one_chunk_ckpt(qc, r0, k_rot, v_rot, src)
        _, (ms, ls, accs) = lax.scan(scan_chunk, None, (qs, row0s))
        m = jnp.moveaxis(ms, 0, -2).reshape(*q.shape[:-2], -1)
        l = jnp.moveaxis(ls, 0, -2).reshape(*q.shape[:-2], -1)
        acc = jnp.moveaxis(accs, 0, -3).reshape(*q.shape[:-2], -1, d)
        return m[..., :s_local], l[..., :s_local], acc[..., :s_local, :]

    def step(carry, t):
        k_rot, v_rot, m_acc, l_acc, acc = carry
        m_b, l_b, acc_b = block(k_rot, v_rot, t)
        m_new = jnp.maximum(m_acc, m_b)
        a1 = jnp.exp(m_acc - m_new)
        a2 = jnp.exp(m_b - m_new)
        l_new = l_acc * a1 + l_b * a2
        acc_new = acc * a1[..., None] + acc_b * a2[..., None]
        k_next = lax.ppermute(k_rot, axis_name, perm)
        v_next = lax.ppermute(v_rot, axis_name, perm)
        return (k_next, v_next, m_new, l_new, acc_new), None

    (kf, vf, m_f, l_f, acc_f), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    l_safe = jnp.where(l_f == 0.0, 1.0, l_f)
    return (acc_f / l_safe[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh, sp_axis="sp", causal=False, sm_scale=None,
                   q_chunk=None):
    """q,k,v: (B, H, S, D) with S sharded over sp_axis; returns same."""
    fn = functools.partial(ring_attention_local, axis_name=sp_axis,
                           causal=causal, sm_scale=sm_scale,
                           q_chunk=q_chunk)
    spec = P(None, None, sp_axis, None)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, axis_names=frozenset({sp_axis}),
                     check_vma=False)(q, k, v)


def sequence_shard(x, mesh, sp_axis="sp", seq_dim=1):
    """Annotate activations sequence-sharded (Megatron-SP style)."""
    spec = [None] * x.ndim
    spec[seq_dim] = sp_axis
    return lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, P(*spec)))
