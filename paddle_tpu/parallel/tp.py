"""Tensor-parallel layers (reference: python/paddle/distributed/fleet/
layers/mpu/mp_layers.py — ColumnParallelLinear etc. over NCCL).

TPU-native: layers carry PartitionSpec annotations on their weights; the
GSPMD partitioner inserts the all-reduce/all-gather that megatron does
by hand. No manual collectives, same math, and XLA can overlap them with
compute on ICI. `gather_output`/`input_is_parallel` map onto output
sharding constraints.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .._core.tensor import Tensor, apply
from ..nn import functional as F
from ..nn.initializer import Constant, XavierUniform, Normal
from ..nn.layer.layers import Layer


def _constrain(x, spec, mesh=None):
    """sharding_constraint as a differentiable op (identity outside jit)."""
    from .mesh import get_mesh
    mesh = mesh or get_mesh()
    if mesh is None:
        return x

    def fn(a):
        try:
            return jax.lax.with_sharding_constraint(
                a, jax.sharding.NamedSharding(mesh, spec))
        except Exception:
            return a
    return apply(fn, x, name="sharding_constraint")


class ColumnParallelLinear(Layer):
    """Weight (in, out) sharded over tp on the out axis."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None, tp_axis="tp"):
        super().__init__()
        self.gather_output = gather_output
        self.tp_axis = tp_axis
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.dist_spec = P(None, tp_axis)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.dist_spec = P(tp_axis)
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            out = _constrain(out, P(None, None, self.tp_axis) if out.ndim == 3
                             else P(None, self.tp_axis))
        return out


class RowParallelLinear(Layer):
    """Weight (in, out) sharded over tp on the in axis; GSPMD inserts the
    psum megatron does explicitly."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None, tp_axis="tp"):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.tp_axis = tp_axis
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.dist_spec = P(tp_axis, None)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.dist_spec = P()
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None, tp_axis="tp"):
        super().__init__()
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 0.02))
        self.weight.dist_spec = P(tp_axis, None)
        self.weight.is_distributed = True

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax CE: with logits sharded over tp on the vocab
    axis GSPMD partitions log_softmax's reductions automatically."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


def mark_sequence_parallel(x, sp_axis="tp", seq_dim=1):
    """Megatron-SP: shard activations' sequence dim over the tp axis
    between attention/MLP blocks (norm/dropout run sequence-sharded)."""
    spec = [None] * x.ndim
    spec[seq_dim] = sp_axis
    return _constrain(x, P(*spec))


def annotate_module_tp(model, rules, tp_axis="tp"):
    """Apply {param-name-glob: PartitionSpec} rules to a Layer tree
    (auto-TP; reference: fleet.meta_parallel tensor_parallel mappings)."""
    import fnmatch
    for name, p in model.named_parameters():
        for pattern, spec in rules.items():
            if fnmatch.fnmatch(name, pattern):
                p.dist_spec = spec if isinstance(spec, P) else P(*spec)
                p.is_distributed = True
                break
    return model
