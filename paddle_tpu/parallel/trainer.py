"""Distributed trainer: the compiled hybrid-parallel train step.

Replaces the reference's fleet.distributed_model + DygraphShardingOptimizer
+ GradScaler orchestration (python/paddle/distributed/fleet/*) with ONE
pjit'd function over the global mesh:

  (params, opt_state, buffers, lr, key, batch) → (params', opt_state',
                                                  buffers', loss)

 * dp: batch sharded over 'dp' (in_shardings) → GSPMD turns the grad
   reduction into a psum over ICI (NCCL allreduce equivalent).
 * tp/sp: carried by param dist_specs + sharding constraints in layers.
 * ZeRO: stage 1/2 shard optimizer slots over dp; stage 3 shards params
   (all-gather on use, reduce-scatter on grad — inserted by XLA).
 * params+opt_state donated: in-place buffer reuse in HBM.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .._core.tensor import Tensor, unwrap
from .._core.state import prng
from ..observability.compile_telemetry import track_jit
from .mesh import fsdp_spec, get_mesh


def _leaf_spec(param_spec, leaf, param_shape):
    """Optimizer slot sharding mirrors its parameter when shapes match."""
    if hasattr(leaf, "shape") and tuple(leaf.shape) == tuple(param_shape):
        return param_spec
    return P()


class Trainer:
    def __init__(self, model, optimizer, loss_fn, mesh=None, batch_spec=None,
                 sharding_stage=0, grad_clip_norm=None, base_seed=1234,
                 donate=True, health_monitor=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        # observability.health.TrainingHealthMonitor (or duck type):
        # when set, the traced step also returns the fused health
        # scalars (loss/nonfinite/grad-norm/update-ratio) and step()
        # feeds them to monitor.observe() — one batched transfer per
        # step, computed in-graph (no per-tensor host syncs)
        self.health_monitor = health_monitor
        self.mesh = mesh or get_mesh()
        if sharding_stage == 0:
            # group_sharded_parallel (ZeRO facade) marks the model/opt;
            # honor it so the paddle API actually shards state
            sharding_stage = getattr(model, "_sharding_stage", 0) or \
                getattr(optimizer, "_sharding_stage", 0)
        self.sharding_stage = sharding_stage
        self.grad_clip_norm = grad_clip_norm
        self.base_seed = base_seed
        self._step_count = 0
        self.batch_spec = batch_spec

        params, buffers = model.functional_state()
        self.param_specs = {}
        named = dict(model.named_parameters())
        for name, p in named.items():
            if p.dist_spec is not None:
                spec = p.dist_spec
            elif sharding_stage >= 3 and self.mesh is not None:
                spec = fsdp_spec(tuple(p._value.shape), self.mesh)
            else:
                spec = P()
            self.param_specs[name] = spec

        if self.mesh is not None:
            params = {n: jax.device_put(v, NamedSharding(self.mesh,
                                                         self.param_specs[n]))
                      for n, v in params.items()}
            # write back so eager model state is also sharded
            for n, v in params.items():
                named[n]._value = v
        self.params = params
        self.buffers = buffers
        self.opt_state = optimizer.init_state(params)
        self.state_specs = jax.tree_util.tree_map(
            lambda leaf: P(), self.opt_state)
        # mirror param specs onto matching-shape slots (ZeRO: shard slots
        # over dp even when params are replicated)
        new_state_specs = {}
        for n, slots in self.opt_state.items():
            pspec = self.param_specs[n]
            pshape = tuple(params[n].shape)
            if sharding_stage in (1, 2) and pspec == P() and self.mesh is not None:
                slot_spec = fsdp_spec(pshape, self.mesh)
            else:
                slot_spec = pspec
            new_state_specs[n] = {k: _leaf_spec(slot_spec, v, pshape)
                                  for k, v in slots.items()}
        self.state_specs = new_state_specs
        if self.mesh is not None:
            self.opt_state = {
                n: {k: jax.device_put(v, NamedSharding(self.mesh,
                                                       self.state_specs[n][k]))
                    for k, v in slots.items()}
                for n, slots in self.opt_state.items()}

        self._jit_step = self._build_step(donate)

    # ------------------------------------------------------------------
    def _build_step(self, donate):
        model = self.model
        loss_fn = self.loss_fn
        optimizer = self.optimizer
        clip_norm = self.grad_clip_norm

        def pure_loss(params, buffers, key, batch):
            with prng.key_ctx(key):
                with model._swapped_state(params, buffers):
                    wrapped = jax.tree_util.tree_map(Tensor, batch)
                    loss = loss_fn(model, wrapped)
                    new_buffers = {n: b._value
                                   for n, b in model.named_buffers()
                                   if b is not None}
            raw = loss._value if isinstance(loss, Tensor) else loss
            return raw.astype(jnp.float32), new_buffers

        with_health = self.health_monitor is not None

        def train_step(params, opt_state, buffers, lr, key, batch):
            (loss, new_buffers), grads = jax.value_and_grad(
                pure_loss, has_aux=True)(params, buffers, key, batch)
            if clip_norm is not None:
                from ..nn.clip import ClipGradByGlobalNorm
                grads, _ = ClipGradByGlobalNorm.functional(grads, clip_norm)
            new_params, new_state = optimizer.apply_gradients(
                params, grads, opt_state, lr)
            health = None
            if with_health:
                # fused in-graph health vector (observability.health):
                # a handful of scalar reductions XLA fuses into the
                # step — observed host-side with ONE batched transfer
                from ..observability.health import health_stats
                health = health_stats(loss, grads, params, new_params)
            return new_params, new_state, new_buffers, loss, health

        if self.mesh is None:
            # compile telemetry: a stable batch shape compiles once; a
            # churning one shows up as retraces on pt_compile_* metrics
            return track_jit("parallel.train_step")(
                jax.jit(train_step,
                        donate_argnums=(0, 1) if donate else ()))

        pspecs = {n: NamedSharding(self.mesh, s)
                  for n, s in self.param_specs.items()}
        sspecs = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), self.state_specs,
            is_leaf=lambda x: isinstance(x, P))
        repl = NamedSharding(self.mesh, P())
        if self.batch_spec is None:
            bspec = repl
        else:
            bspec = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s), self.batch_spec,
                is_leaf=lambda x: isinstance(x, P))

        return track_jit("parallel.train_step")(jax.jit(
            train_step,
            in_shardings=(pspecs, sspecs, None, None, None, bspec),
            out_shardings=(pspecs, sspecs, None, repl,
                           None if not with_health else
                           {"loss": repl, "nonfinite": repl,
                            "grad_norm": repl, "update_ratio": repl}),
            donate_argnums=(0, 1) if donate else ()))

    # ------------------------------------------------------------------
    def step(self, batch):
        """batch: pytree of numpy/jax arrays (already batched)."""
        batch = jax.tree_util.tree_map(
            lambda t: unwrap(t) if isinstance(t, Tensor) else jnp.asarray(t),
            batch, is_leaf=lambda t: isinstance(t, Tensor))
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = jax.random.fold_in(jax.random.key(self.base_seed), self._step_count)
        (self.params, self.opt_state, self.buffers, loss,
         health) = self._jit_step(
            self.params, self.opt_state, self.buffers, lr, key, batch)
        self._step_count += 1
        if self.health_monitor is not None and health is not None:
            self.health_monitor.observe(health, step=self._step_count)
        from ..optimizer.lr import LRScheduler
        if isinstance(self.optimizer._learning_rate, LRScheduler):
            self.optimizer._learning_rate.step()
        return loss

    def sync_model(self):
        """Copy trained params back into the eager model tree."""
        named = dict(self.model.named_parameters())
        for n, v in self.params.items():
            named[n]._value = v
        namedb = dict(self.model.named_buffers())
        for n, v in self.buffers.items():
            if n in namedb and namedb[n] is not None:
                namedb[n]._value = v
