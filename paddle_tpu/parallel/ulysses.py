"""Ulysses-style all-to-all sequence parallelism.

Reference parity: the fork's context-parallel attention utilities
(python/paddle/distributed/fleet/layers/mpu + ring attention in
PaddleNLP) ship ring P2P context parallelism; DeepSpeed-Ulysses-style
all-to-all is its standard alternative. TPU-native design: the two
lax.all_to_all re-shards ride ICI as XLA collectives — no NCCL, no
hand-written P2P.

Scheme (inside shard_map over the `sp` mesh axis, n devices):

    (B, H, S/n, D)  --all_to_all-->  (B, H/n, S, D)
    full flash attention per device (exact causal — every device holds
    the ENTIRE sequence for its head slice, so no cross-device masking
    logic at all, and the pallas kernel's causal block-skip applies)
    (B, H/n, S, D)  --all_to_all-->  (B, H, S/n, D)

vs ring attention (parallel/ring.py): ring keeps K/V moving n-1 hops
and masks per-block; Ulysses moves q/k/v/o once each and runs the
plain kernel at full context. Ulysses wins while heads >= n (wire
bytes comparable, far better kernel efficiency); ring is the fallback
when sequence must scale past the head count.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from .._core.compat import axis_size, shard_map

from ..ops.flash_attention import flash_attention_bhsd


def ulysses_attention_local(q, k, v, axis_name, causal=False, sm_scale=None):
    """Runs INSIDE shard_map: q (B, H, S_local, D) sequence-sharded over
    `axis_name`, H divisible by the axis size. k/v may carry FEWER
    (GQA) heads: when kv_heads is also divisible by the axis size they
    ride the all-to-all at kv width and are repeated to full head count
    only AFTER the re-shard — nh/nkv times fewer K/V wire bytes than
    repeating up front. Returns (B, H, S_local, D), same sharding."""
    n = axis_size(axis_name)
    H, Hkv = q.shape[1], k.shape[1]
    if H % n:
        raise ValueError(
            f"ulysses attention needs heads ({H}) divisible by the sp "
            f"axis size ({n}); use ring attention to scale sequence "
            "past the head count")
    if v.shape[1] != Hkv or H % Hkv:
        raise ValueError(
            f"k/v head counts ({Hkv}, {v.shape[1]}) must match and "
            f"divide q heads ({H})")
    if Hkv != H and Hkv % n:
        # kv heads cannot shard over the axis — repeat up front and pay
        # the wire cost rather than refuse
        k = jnp.repeat(k, H // Hkv, axis=1)
        v = jnp.repeat(v, H // Hkv, axis=1)
    a2a = functools.partial(lax.all_to_all, axis_name=axis_name, tiled=True)
    # heads scatter, sequence gathers: received seq chunks concatenate
    # in device order = global token order
    qh = a2a(q, split_axis=1, concat_axis=2)
    kh = a2a(k, split_axis=1, concat_axis=2)
    vh = a2a(v, split_axis=1, concat_axis=2)
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    o = flash_attention_bhsd(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return a2a(o, split_axis=2, concat_axis=1)


def ulysses_attention(q, k, v, mesh, sp_axis="sp", causal=False,
                      sm_scale=None):
    """q, k, v: (B, H, S, D) with S sharded over sp_axis; returns same."""
    fn = functools.partial(ulysses_attention_local, axis_name=sp_axis,
                           causal=causal, sm_scale=sm_scale)
    spec = P(None, None, sp_axis, None)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, axis_names=frozenset({sp_axis}),
                     check_vma=False)(q, k, v)
