"""Profiler (reference: python/paddle/profiler/profiler.py).

Wraps jax.profiler: traces are Perfetto/XPlane (TensorBoard-compatible),
replacing the reference's CUPTI/nvprof collection. summary() reports
host-side op timings from our dispatch-layer TraceEvent ring.

Scheduled capture: `Profiler(scheduler=make_scheduler(...))` drives
CLOSED → READY → RECORD windows from `step()` — warmup (READY) events
are excluded from the exported session, each RECORD window ends by
firing `on_trace_ready` (and, with an `export_chrome_tracing` handler,
writing this session's chrome-tracing JSON), and `repeat` cycles each
produce their own export.

Spans (`record_span` / `RecordEvent`) carry the observability layer's
trace context: the current request's trace id plus parent/child span
ids, and every finished span also lands in the crash flight recorder
(`paddle_tpu.observability.flight_recorder`).
"""
from __future__ import annotations

import contextlib
import enum
import os
import time

import jax


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys(enum.Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        total = closed + ready + record
        if repeat and s >= total * repeat:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready handler: export each finished RECORD window's
    host-side trace as chrome-tracing JSON under `dir_name` (one file
    per window: <worker>.pt_trace.<n>.json)."""
    def handler(prof):
        prof._export_dir = dir_name
        prof._export_worker = worker_name
        prof._export_session()
    return handler


export_protobuf = export_chrome_tracing

_RECORDING = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self._dir = os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/pt_profile")
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        # profile_memory: poll the device-memory accountant on every
        # recorded step — snapshots land as `device.memory` flight
        # events next to the window's spans (reference: the profiler's
        # MemoryView, rebuilt on memory_stats + live_arrays)
        self._profile_memory = bool(profile_memory)
        self._active = False        # a jax.profiler device trace is live
        self._recording = False     # a host RECORD window is open
        self._state = ProfilerState.CLOSED
        self._step = 0
        self._step_times = []
        self._last = None
        self._export_dir = None
        self._export_worker = None
        self._export_seq = 0

    # -- capture windows ----------------------------------------------
    def _open_window(self):
        # host event ring: windows export only events recorded after
        # this timestamp — earlier sessions' spans must not leak in
        self._t_session = time.time()
        self._recording = True
        if not self._timer_only:
            try:
                jax.profiler.start_trace(self._dir)
                self._active = True
            except Exception:
                self._active = False

    def _close_window(self, ready=True):
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
        self._recording = False
        if ready and self._on_trace_ready:
            self._on_trace_ready(self)

    def _export_session(self):
        """Write the current window's chrome trace into the handler's
        dir (wired by export_chrome_tracing); returns the path."""
        if not self._export_dir:
            return None
        os.makedirs(self._export_dir, exist_ok=True)
        worker = self._export_worker or f"host_{os.getpid()}"
        self._export_seq += 1
        path = os.path.join(self._export_dir,
                            f"{worker}.pt_trace.{self._export_seq}.json")
        self.export(path)
        return path

    # -- lifecycle -----------------------------------------------------
    def start(self):
        from ..utils import trace as _trace
        self._prev_trace_enabled = _trace.enabled()
        _trace.enable()
        self._t_session = time.time()
        if self._scheduler is not None:
            self._state = self._scheduler(0)
        else:
            self._state = ProfilerState.RECORD
        if self._state in _RECORDING:
            self._open_window()
        self._last = time.perf_counter()

    def stop(self):
        if self._recording:
            self._close_window(ready=True)
        elif self._scheduler is None and self._on_trace_ready:
            self._on_trace_ready(self)   # legacy: handler always fires
        self._state = ProfilerState.CLOSED
        if not getattr(self, "_prev_trace_enabled", True):
            from ..utils import trace as _trace
            _trace.disable()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._step_times.append(now - self._last)
        self._last = now
        self._step += 1
        if self._profile_memory and self._recording:
            from ..observability.device_telemetry import ACCOUNTANT
            ACCOUNTANT.poll()   # rate-limited live-array walk
        if self._scheduler is None:
            return
        old = self._state
        new = self._scheduler(self._step)
        self._state = new
        if self._recording and (old is ProfilerState.RECORD_AND_RETURN
                                or new not in _RECORDING):
            # the window just finished (AND_RETURN marks the last
            # recorded step of a cycle): hand the trace over now, so a
            # `repeat` schedule exports one file per cycle
            self._close_window(ready=True)
        if new in _RECORDING and not self._recording:
            self._open_window()

    @property
    def current_state(self):
        return self._state

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        ts = np.asarray(self._step_times[-10:])
        return (f"avg step {ts.mean()*1000:.2f} ms, ips "
                f"{1.0/ts.mean():.2f} steps/s")

    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True,
                thread_sep=False, time_unit="ms", views=None):
        from ..utils.trace import summary as trace_summary
        print(trace_summary())

    def export(self, path, format="json"):
        """Write THIS session's host-side events (RecordEvent spans +
        dispatch-layer op spans fed by _core.apply when tracing is on)
        as chrome://tracing JSON. On-chip XLA traces captured by
        start_trace live under self._dir for TensorBoard/XProf."""
        if format not in ("json", "chrome"):
            raise ValueError(
                f"unsupported export format {format!r}: only chrome-"
                "tracing 'json' is implemented (XLA device traces are "
                "XPlane dumps under the profiler dir)")
        import json as _json

        from ..observability.chrome_trace import chrome_trace_doc
        from ..utils import trace as _trace
        t0 = getattr(self, "_t_session", 0.0)
        spans = []
        for ev in _trace.events():
            if ev.ts_end < t0:
                continue  # a previous session's span
            args = dict(ev.args or {})
            if ev.shape is not None:
                args["shape"] = str(ev.shape)
            spans.append({"name": ev.name, "t_start": ev.ts_end - ev.dur,
                          "dur_s": ev.dur, "trace_id": ev.trace_id,
                          "span_id": ev.span_id,
                          "parent_id": ev.parent_id,
                          "args": args or None})
        with open(path, "w") as f:
            _json.dump(chrome_trace_doc(spans), f)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


def record_span(name, args=None):
    """A RecordEvent as a with-block: annotates the device trace (when
    one is being captured), feeds the host event ring (when tracing
    is enabled), and drops a span — stamped with the current trace
    context — into the crash flight recorder. The serving engine wraps
    its prefill/decode/verify device calls in these, so a Profiler
    session over a serving workload attributes wall-clock to engine
    phases. Near-free when no profiler is active.

        with profiler.record_span("serving.decode_step"):
            ...
    """
    return RecordEvent(name, args=args)


class RecordEvent:
    def __init__(self, name, event_type=None, args=None):
        self.name = name
        self.args = args
        self._ctx = None
        self._span = None

    def begin(self):
        from ..observability import trace_context as _tc
        self._span = _tc.span(self.name, args=self.args)
        self._span.__enter__()
        try:
            self._ctx = jax.profiler.TraceAnnotation(self.name)
            self._ctx.__enter__()
        except Exception:
            self._ctx = None

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
        if self._span is not None:
            # feeds the host ring (gated: Profiler.start enables tracing
            # for its session; PADDLE_TPU_TRACE=1 enables it globally)
            # and the flight recorder (always; bounded ring)
            self._span.__exit__(None, None, None)
            self._span = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def load_profiler_result(filename):
    raise NotImplementedError("load XPlane dumps with TensorBoard")


class SummaryView:
    """reference: profiler.SummaryView enum (table selection)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8
