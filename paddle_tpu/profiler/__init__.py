"""Profiler (reference: python/paddle/profiler/profiler.py).

Wraps jax.profiler: traces are Perfetto/XPlane (TensorBoard-compatible),
replacing the reference's CUPTI/nvprof collection. summary() reports
host-side op timings from our dispatch-layer TraceEvent ring.
"""
from __future__ import annotations

import contextlib
import enum
import os
import time

import jax


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys(enum.Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        total = closed + ready + record
        if repeat and s >= total * repeat:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._export_dir = dir_name
    return handler


export_protobuf = export_chrome_tracing


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self._dir = os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/pt_profile")
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._active = False
        self._step = 0
        self._step_times = []
        self._last = None

    def start(self):
        # host event ring: sessions enable tracing for their duration
        # (restoring the prior state on stop) and export only events
        # recorded after this timestamp — earlier sessions' spans must
        # not leak into this session's trace
        from ..utils import trace as _trace
        self._prev_trace_enabled = _trace.enabled()
        _trace.enable()
        self._t_session = time.time()
        if not self._timer_only:
            try:
                jax.profiler.start_trace(self._dir)
                self._active = True
            except Exception:
                self._active = False
        self._last = time.perf_counter()

    def stop(self):
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
        if not getattr(self, "_prev_trace_enabled", True):
            from ..utils import trace as _trace
            _trace.disable()
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._step_times.append(now - self._last)
        self._last = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        ts = np.asarray(self._step_times[-10:])
        return (f"avg step {ts.mean()*1000:.2f} ms, ips "
                f"{1.0/ts.mean():.2f} steps/s")

    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True,
                thread_sep=False, time_unit="ms", views=None):
        from ..utils.trace import summary as trace_summary
        print(trace_summary())

    def export(self, path, format="json"):
        """Write THIS session's host-side events (RecordEvent spans +
        dispatch-layer op spans fed by _core.apply when tracing is on)
        as chrome://tracing JSON. On-chip XLA traces captured by
        start_trace live under self._dir for TensorBoard/XProf."""
        if format not in ("json", "chrome"):
            raise ValueError(
                f"unsupported export format {format!r}: only chrome-"
                "tracing 'json' is implemented (XLA device traces are "
                "XPlane dumps under the profiler dir)")
        import json as _json
        from ..utils import trace as _trace
        t0 = getattr(self, "_t_session", 0.0)
        evts = []
        for name, dur, shape, ts_end in _trace.events():
            if ts_end < t0:
                continue  # a previous session's span
            e = {"name": name, "ph": "X", "pid": 0, "tid": 0,
                 "ts": (ts_end - dur) * 1e6, "dur": dur * 1e6}
            if shape is not None:
                e["args"] = {"shape": str(shape)}
            evts.append(e)
        with open(path, "w") as f:
            _json.dump({"traceEvents": evts,
                        "displayTimeUnit": "ms"}, f)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


def record_span(name):
    """A RecordEvent as a with-block: annotates the device trace (when
    one is being captured) and feeds the host event ring (when tracing
    is enabled). The serving engine wraps its prefill/decode/verify
    device calls in these, so a Profiler session over a serving
    workload attributes wall-clock to engine phases. Near-free when no
    profiler is active.

        with profiler.record_span("serving.decode_step"):
            ...
    """
    return RecordEvent(name)


class RecordEvent:
    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter()
        try:
            self._ctx = jax.profiler.TraceAnnotation(self.name)
            self._ctx.__enter__()
        except Exception:
            self._ctx = None

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
        if self._t0 is not None:
            # feed the host ring (gated: Profiler.start enables tracing
            # for its session; PADDLE_TPU_TRACE=1 enables it globally)
            from ..utils import trace as _trace
            if _trace.enabled():
                _trace.record(self.name, time.perf_counter() - self._t0)
            self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def load_profiler_result(filename):
    raise NotImplementedError("load XPlane dumps with TensorBoard")


class SummaryView:
    """reference: profiler.SummaryView enum (table selection)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8
