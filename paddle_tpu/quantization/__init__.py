"""Quantization (reference: python/paddle/quantization + incubate
weight-only quant).

Round-1 scope: weight-only int8/int4 PTQ for inference matmuls —
quantize to per-channel int8, dequantize inside the matmul (XLA fuses
the dequant into the MXU feed). QAT API surface stubbed.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor, apply, unwrap
from ..nn.layer.layers import Layer


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """→ (quantized int8 weights, per-out-channel fp scales).
    Weight layout (in, out); scales over the out axis."""
    w = unwrap(x).astype(jnp.float32)
    if algo in ("weight_only_int8", "llm.int8"):
        scale = jnp.max(jnp.abs(w), axis=0) / 127.0
        q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-10)), -127, 127) \
            .astype(jnp.int8)
        return Tensor(q), Tensor(scale)
    if algo == "weight_only_int4":
        scale = jnp.max(jnp.abs(w), axis=0) / 7.0
        q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-10)), -7, 7) \
            .astype(jnp.int8)
        return Tensor(q), Tensor(scale)
    raise ValueError(f"unknown algo {algo}")


def weight_dequantize(x, scale, algo="weight_only_int8"):
    return apply(lambda q, s: q.astype(jnp.float32) * s, x, scale,
                 name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(Wq) + b (reference: incubate weight_only_linear)."""
    def fn(a, q, s, *b):
        w = q.astype(a.dtype) * s.astype(a.dtype)
        out = a @ w
        if b:
            out = out + b[0]
        return out
    args = [x, weight, weight_scale]
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="weight_only_linear")


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer2config = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        self._layer2config[id(layer)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        pass

    def add_name_config(self, names, activation=None, weight=None):
        pass


class QAT:
    """Quantization-aware training scaffold (full fake-quant round 2)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        return model

    def convert(self, model, inplace=False):
        return model


class PTQ:
    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=False):
        """Replace Linear weights with int8 + scale (weight-only)."""
        from ..nn.layer.common import Linear
        for _, layer in model.named_sublayers(include_self=True):
            if isinstance(layer, Linear) and layer.weight is not None:
                q, s = weight_quantize(layer.weight)
                layer._quant_weight = q
                layer._quant_scale = s
                layer._orig_forward = layer.forward

                def make_fwd(l):
                    def fwd(inp):
                        return weight_only_linear(inp, l._quant_weight, l.bias,
                                                  l._quant_scale)
                    return fwd
                object.__setattr__(layer, "forward", make_fwd(layer))
        return model

    def convert(self, model, inplace=False):
        return model


class QuantizedLinear(Layer):
    def __init__(self, in_features, out_features, weight_dtype="int8"):
        super().__init__()
        import jax.numpy as jnp
        self.register_buffer("quant_weight", Tensor(
            jnp.zeros((in_features, out_features), jnp.int8)))
        self.register_buffer("quant_scale", Tensor(
            jnp.ones((out_features,), jnp.float32)))
        self.bias = self.create_parameter([out_features], is_bias=True)

    @classmethod
    def from_linear(cls, linear):
        q = cls(linear.weight.shape[0], linear.weight.shape[1])
        qw, s = weight_quantize(linear.weight)
        q.quant_weight.set_value(qw)
        q.quant_scale.set_value(s)
        if linear.bias is not None:
            q.bias.set_value(linear.bias)
        return q

    def forward(self, x):
        return weight_only_linear(x, self.quant_weight, self.bias,
                                  self.quant_scale)
