"""Quantization (reference: python/paddle/quantization + incubate
weight-only quant).

Round-1 scope: weight-only int8/int4 PTQ for inference matmuls —
quantize to per-channel int8, dequantize inside the matmul (XLA fuses
the dequant into the MXU feed). QAT API surface stubbed.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor, apply, unwrap
from ..nn.layer.layers import Layer


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """→ (quantized int8 weights, per-out-channel fp scales).
    Weight layout (in, out); scales over the out axis."""
    w = unwrap(x).astype(jnp.float32)
    if algo in ("weight_only_int8", "llm.int8"):
        scale = jnp.max(jnp.abs(w), axis=0) / 127.0
        q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-10)), -127, 127) \
            .astype(jnp.int8)
        return Tensor(q), Tensor(scale)
    if algo == "weight_only_int4":
        scale = jnp.max(jnp.abs(w), axis=0) / 7.0
        q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-10)), -7, 7) \
            .astype(jnp.int8)
        return Tensor(q), Tensor(scale)
    raise ValueError(f"unknown algo {algo}")


def weight_dequantize(x, scale, algo="weight_only_int8"):
    return apply(lambda q, s: q.astype(jnp.float32) * s, x, scale,
                 name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(Wq) + b (reference: incubate weight_only_linear)."""
    def fn(a, q, s, *b):
        w = q.astype(a.dtype) * s.astype(a.dtype)
        out = a @ w
        if b:
            out = out + b[0]
        return out
    args = [x, weight, weight_scale]
    if bias is not None:
        args.append(bias)
    return apply(fn, *args, name="weight_only_linear")


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer2config = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        self._layer2config[id(layer)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        pass

    def add_name_config(self, names, activation=None, weight=None):
        pass


def _qdq_ste(x, scale, qmax):
    """Quantize-dequantize with a straight-through estimator: the value is
    the rounded/clipped int grid point, the gradient flows as identity."""
    s = jnp.maximum(scale, 1e-10)
    qdq = jnp.clip(jnp.round(x / s), -qmax, qmax) * s
    return x + jax.lax.stop_gradient(qdq - x)


class FakeQuanterChannelWiseAbsMax:
    """Weight fake-quant: per-out-channel absmax scale, recomputed each
    step from the live weight (reference: quanter ChannelWiseAbsMax)."""

    def __init__(self, bits=8):
        self.qmax = (1 << (bits - 1)) - 1

    def __call__(self, w):
        scale = jnp.max(jnp.abs(jax.lax.stop_gradient(w)), axis=0,
                        keepdims=True) / self.qmax
        return _qdq_ste(w, scale, self.qmax)


class FakeQuanterMovingAverageAbsMax:
    """Activation fake-quant: EMA of the batch absmax (reference:
    FakeQuanterWithAbsMaxObserver). State is a python float on the layer —
    updated eagerly during QAT (which trains eagerly here)."""

    def __init__(self, bits=8, momentum=0.9):
        self.qmax = (1 << (bits - 1)) - 1
        self.momentum = momentum
        self.running_absmax = None

    def __call__(self, x, training=True):
        cur = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
        if training or self.running_absmax is None:
            try:
                curf = float(cur)
                self.running_absmax = (curf if self.running_absmax is None
                                       else self.momentum * self.running_absmax
                                       + (1 - self.momentum) * curf)
            except Exception:
                pass  # traced: fall back to the current batch stat
        ref = (jnp.asarray(self.running_absmax, jnp.float32)
               if self.running_absmax is not None else cur)
        return _qdq_ste(x, ref / self.qmax, self.qmax)


class QAT:
    """Quantization-aware training (reference: quantization/qat.py:27).

    quantize(): wraps each Linear so its forward computes with fake-
    quantized weights and activations (STE gradients) — training sees
    int8 noise while staying fp.
    convert(): unwraps and swaps each trained Linear for the int8
    weight-only QuantizedLinear the PTQ path uses at inference.
    """

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=False):
        from ..nn.layer.common import Linear
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        for _, layer in model.named_sublayers(include_self=True):
            if isinstance(layer, Linear) and layer.weight is not None \
                    and not hasattr(layer, "_qat_wq"):
                layer._qat_wq = FakeQuanterChannelWiseAbsMax()
                layer._qat_aq = FakeQuanterMovingAverageAbsMax()
                layer._orig_forward = layer.forward

                def make_fwd(l):
                    def fwd(inp):
                        def fn(a, w, *b):
                            af = l._qat_aq(a, training=l.training)
                            wf = l._qat_wq(w)
                            out = af @ wf
                            if b:
                                out = out + b[0]
                            return out
                        args = [inp, l.weight]
                        if l.bias is not None:
                            args.append(l.bias)
                        return apply(fn, *args, name="qat_linear")
                    return fwd
                object.__setattr__(layer, "forward", make_fwd(layer))
        return model

    def convert(self, model, inplace=False):
        """Swap QAT-wrapped Linears for int8 weight-only inference
        layers."""
        from ..nn.layer.common import Linear
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        for _, parent in model.named_sublayers(include_self=True):
            for name, child in list(parent.named_children()):
                if isinstance(child, Linear) and hasattr(child, "_qat_wq"):
                    object.__setattr__(child, "forward",
                                       child._orig_forward)
                    setattr(parent, name, QuantizedLinear.from_linear(child))
        if isinstance(model, Linear) and hasattr(model, "_qat_wq"):
            object.__setattr__(model, "forward", model._orig_forward)
            return QuantizedLinear.from_linear(model)
        return model


class PTQ:
    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=False):
        """Replace Linear weights with int8 + scale (weight-only)."""
        from ..nn.layer.common import Linear
        for _, layer in model.named_sublayers(include_self=True):
            if isinstance(layer, Linear) and layer.weight is not None:
                q, s = weight_quantize(layer.weight)
                layer._quant_weight = q
                layer._quant_scale = s
                layer._orig_forward = layer.forward

                def make_fwd(l):
                    def fwd(inp):
                        return weight_only_linear(inp, l._quant_weight, l.bias,
                                                  l._quant_scale)
                    return fwd
                object.__setattr__(layer, "forward", make_fwd(layer))
        return model

    def convert(self, model, inplace=False):
        return model


class QuantizedLinear(Layer):
    def __init__(self, in_features, out_features, weight_dtype="int8"):
        super().__init__()
        import jax.numpy as jnp
        self.register_buffer("quant_weight", Tensor(
            jnp.zeros((in_features, out_features), jnp.int8)))
        self.register_buffer("quant_scale", Tensor(
            jnp.ones((out_features,), jnp.float32)))
        self.bias = self.create_parameter([out_features], is_bias=True)

    @classmethod
    def from_linear(cls, linear):
        q = cls(linear.weight.shape[0], linear.weight.shape[1])
        qw, s = weight_quantize(linear.weight)
        q.quant_weight.set_value(qw)
        q.quant_scale.set_value(s)
        if linear.bias is not None:
            q.bias.set_value(linear.bias)
        return q

    def forward(self, x):
        return weight_only_linear(x, self.quant_weight, self.bias,
                                  self.quant_scale)


class BaseObserver:
    """reference: quantization/base_observer.py — collects activation
    statistics during calibration; subclasses implement cal_thresholds."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._min = None
        self._max = None

    def observe(self, tensor):
        import numpy as np
        from .._core.tensor import unwrap as _uw
        v = np.asarray(_uw(tensor))
        lo, hi = float(v.min()), float(v.max())
        self._min = lo if self._min is None else min(self._min, lo)
        self._max = hi if self._max is None else max(self._max, hi)
        return tensor

    __call__ = observe

    def cal_thresholds(self):
        return self._min, self._max

    def scales(self):
        m = max(abs(self._min or 0.0), abs(self._max or 0.0))
        return m / (2 ** (self.quant_bits - 1) - 1)


class BaseQuanter(BaseObserver):
    """reference: quantization/base_quanter.py — a fake-quant module the
    QAT pass inserts; quantize-dequantize with the observed scale."""

    def __call__(self, tensor):
        import jax.numpy as jnp
        from .._core.tensor import apply as _apply
        self.observe(tensor)
        s = self.scales() or 1e-8
        qmax = 2 ** (self.quant_bits - 1) - 1

        def fn(v):
            q = jnp.clip(jnp.round(v / s), -qmax - 1, qmax)
            return (q * s).astype(v.dtype)
        return _apply(fn, tensor, name="fake_quant")


def quanter(name):
    """reference: quantization/factory.py quanter decorator — register a
    quanter class under a config name."""
    def decorator(cls):
        _QUANTER_REGISTRY[name] = cls
        return cls
    return decorator


_QUANTER_REGISTRY = {}
