"""Legacy reader-decorator API (reference: python/paddle/reader/decorator.py).

Paddle 1.x-era composable data readers: a *reader* is a zero-arg callable
returning a generator of samples. Kept for migration parity; new code
should use paddle_tpu.io.DataLoader (threaded/process prefetch + libptio).
"""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading

__all__ = [
    "cache", "map_readers", "shuffle", "chain", "compose", "buffered",
    "firstn", "xmap_readers", "multiprocess_reader", "ComposeNotAligned",
]


class _ReaderError:
    """In-band marker carrying a producer-thread exception to the
    consumer — a failed reader must raise, not truncate the stream."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def cache(reader):
    """Cache all samples in memory on first *complete* epoch; replay
    thereafter. A partially-consumed first epoch leaves the cache unfilled
    (next call re-reads the source) rather than accumulating duplicates."""
    state = {"data": None}

    def rd():
        if state["data"] is not None:
            yield from state["data"]
            return
        epoch = []
        for item in reader():
            epoch.append(item)
            yield item
        state["data"] = epoch  # only reached when fully drained

    return rd


def map_readers(func, *readers):
    """Yield func(*one_sample_from_each_reader) lockstep over readers."""

    def rd():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return rd


def shuffle(reader, buf_size):
    """Pool-shuffle with a bounded buffer (reference decorator.py:202)."""

    def rd():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return rd


def chain(*readers):
    """Concatenate readers back to back."""

    def rd():
        for r in readers:
            yield from r()

    return rd


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment=True):
    """Zip readers into flattened tuples: (a, (b, c)) → (a, b, c)."""

    def _flatten(item):
        if isinstance(item, tuple):
            out = []
            for x in item:
                out.extend(_flatten(x))
            return tuple(out)
        return (item,)

    def rd():
        iters = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*iters):
                if any(i is None for i in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((_flatten(i) for i in items), ())
        else:
            for items in zip(*iters):
                yield sum((_flatten(i) for i in items), ())

    return rd


def buffered(reader, size):
    """Producer thread fills a bounded queue; consumer drains — overlaps
    read latency with downstream compute."""

    end = object()

    def rd():
        q = queue.Queue(maxsize=size)

        def produce():
            try:
                for item in reader():
                    q.put(item)
                q.put(end)
            except BaseException as e:  # forward, never truncate silently
                q.put(_ReaderError(e))

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                break
            if isinstance(item, _ReaderError):
                raise item.exc
            yield item

    return rd


def firstn(reader, n):
    def rd():
        return itertools.islice(reader(), n)

    return rd


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (reference
    decorator.py:476). order=True preserves input order."""

    end = object()

    def rd():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            try:
                for i, item in enumerate(reader()):
                    in_q.put((i, item))
            except BaseException as e:  # source failed: tell the consumer
                out_q.put(("__xmap_error__", e))  # (workers stay parked)
                return
            for _ in range(process_num):
                in_q.put(end)

        done = [0]
        lock = threading.Lock()

        def work():
            while True:
                got = in_q.get()
                if got is end:
                    with lock:
                        done[0] += 1
                        if done[0] == process_num:
                            out_q.put(end)
                    return
                i, item = got
                try:
                    out = mapper(item)
                except BaseException as e:  # forward to consumer, don't
                    out_q.put(("__xmap_error__", e))  # strand the sentinel
                    return
                out_q.put((i, out))

        threads = [threading.Thread(target=feed, daemon=True)]
        threads += [threading.Thread(target=work, daemon=True)
                    for _ in range(process_num)]
        for t in threads:
            t.start()

        def _next():
            got = out_q.get()
            if got is not end and got[0] == "__xmap_error__":
                raise got[1]
            return got

        if not order:
            while True:
                got = _next()
                if got is end:
                    break
                yield got[1]
        else:
            pending, want = {}, 0
            while True:
                got = _next()
                if got is end:
                    break
                pending[got[0]] = got[1]
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            # end only arrives after every worker drained (and error paths
            # raise before it), so the ordered stream must be complete here
            assert not pending, "xmap_readers: index gap at end of stream"

    return rd


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave several readers, each drained on its own thread.

    The reference forks OS processes and shuttles samples over pipes;
    on TPU hosts the heavy decode belongs in DataLoader's process
    workers / libptio, so this shim keeps the API and the interleaving
    semantics with threads (samples arrive in completion order)."""
    assert len(readers) > 0

    def rd():
        q = queue.Queue(queue_size)
        end = object()

        def drain(r):
            try:
                for item in r():
                    q.put(item)
                q.put(end)
            except BaseException as e:
                q.put(_ReaderError(e))

        for r in readers:
            threading.Thread(target=drain, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            item = q.get()
            if item is end:
                finished += 1
            elif isinstance(item, _ReaderError):
                raise item.exc
            else:
                yield item

    return rd
