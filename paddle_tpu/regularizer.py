"""Weight-decay regularizers (reference: python/paddle/regularizer.py)."""
from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff


class L2Decay(WeightDecayRegularizer):
    """grad += coeff * param (applied in optimizer update)."""

    def __call__(self, param_raw, grad_raw):
        return grad_raw + self._coeff * param_raw


class L1Decay(WeightDecayRegularizer):
    def __call__(self, param_raw, grad_raw):
        import jax.numpy as jnp
        return grad_raw + self._coeff * jnp.sign(param_raw)
