"""paddle_tpu.serving — production serving runtime over the
continuous-batching engine (`models/llama_serving.ServingEngine`).

Layers (docs/serving.md has the architecture):

  * `metrics`   — counters/gauges/histograms registry; Prometheus text
                  exposition + JSON snapshot; `EngineMetrics` is the
                  hook object the engine's step loop reports into.
  * `kvcache`   — ref-counted page pool + radix prefix cache: requests
                  sharing a prompt prefix share physical KV pages and
                  prefill only their suffix (host-side numpy, no
                  device or model imports).
  * `scheduler` — thread-safe bounded request queue with priority
                  classes, deadlines/TTLs, cancellation, backpressure
                  (`BackpressureError`), and graceful drain.
  * `server`    — stdlib ThreadingHTTPServer frontend: streaming
                  `/v1/completions`, `/healthz`, `/metrics`.
  * `client`    — stdlib HTTP client, SSE streaming included.

This package never imports the model/engine modules at import time —
the engine arrives as a constructor argument — so
`import paddle_tpu.serving` stays cheap and cycle-free.
"""
from __future__ import annotations

from . import client, kvcache, metrics, scheduler, server  # noqa: F401
from .client import ServingClient, ServingHTTPError  # noqa: F401
from .kvcache import PagePool, PrefixCache  # noqa: F401
from .metrics import (  # noqa: F401
    Counter, EngineMetrics, Gauge, Histogram, MetricsRegistry,
)
from .scheduler import (  # noqa: F401
    BackpressureError, DeadlineExceededError, RequestScheduler,
    SchedulerClosedError, SchedulerError, ServingRequest,
)
from .server import ServingServer  # noqa: F401

__all__ = [
    "client", "kvcache", "metrics", "scheduler", "server",
    "ServingClient", "ServingHTTPError",
    "PagePool", "PrefixCache",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "EngineMetrics",
    "RequestScheduler", "ServingRequest", "SchedulerError",
    "BackpressureError", "DeadlineExceededError", "SchedulerClosedError",
    "ServingServer",
]
