"""paddle_tpu.serving — production serving runtime over the
continuous-batching engine (`models/llama_serving.ServingEngine`).

Layers (docs/serving.md has the architecture):

  * `metrics`   — counters/gauges/histograms registry; Prometheus text
                  exposition + JSON snapshot; `EngineMetrics` is the
                  hook object the engine's step loop reports into.
  * `kvcache`   — ref-counted page pool + radix prefix cache: requests
                  sharing a prompt prefix share physical KV pages and
                  prefill only their suffix (host-side numpy, no
                  device or model imports).
  * `kvtier`    — bounded host-RAM KV tier under the prefix cache:
                  LRU evictions demote pages to host memory
                  (int8-quantized, async copies off the pump thread),
                  lookups fall through device -> host, and the
                  preemption offload stash shares the bytes ledger.
  * `handoff`   — KV-page handoff payloads (`KVHandoff`) for
                  disaggregated prefill/decode serving: a prefill-role
                  replica exports a prefilled request's pages, the
                  router migrates it to a decode-role replica
                  (plain numpy + ints, transport-agnostic).
  * `faults`    — deterministic fault injection: a seeded `FaultPlan`
                  (PT_FAULTS / constructor) armed at the stack's real
                  failure sites, so chaos drills replay byte-for-byte
                  (docs/reliability.md).
  * `timeline`  — per-request phase timelines (host-clock marks that
                  survive preemption, crash requeue, and cross-replica
                  migration), SLO classes + violation attribution, and
                  the step-time anomaly sentinel.
  * `scheduler` — thread-safe bounded request queue with priority
                  classes, deadlines/TTLs, cancellation, backpressure
                  (`BackpressureError`), and graceful drain.
  * `replica`   — one engine + scheduler + metrics registry behind the
                  transport-agnostic surface the router dispatches to.
  * `router`    — scale-out tier: consistent-hash prefix-affinity
                  dispatch across N replicas, least-loaded spill,
                  circuit-breaker health, pre-output failover, and
                  graceful per-replica drain.
  * `wire`      — length-framed socket framing for the fleet bulk
                  channel: JSON control frames + raw numpy arrays,
                  never pickle.
  * `fleet`     — multi-host plane over `distributed/rpc.py`:
                  `FleetWorker` processes serve replicas remotely,
                  `RemoteReplica` proxies satisfy the `Replica`
                  duck-type for an unchanged `Router`, KV handoffs
                  and spilled prefix pages move host-to-host over a
                  bulk channel (one global prefix cache).
  * `server`    — stdlib ThreadingHTTPServer frontend: streaming
                  `/v1/completions`, `/healthz`, `/readyz`,
                  `/metrics`; mounts a scheduler OR a router.
  * `client`    — stdlib HTTP client, SSE streaming included.

This package never imports the model/engine modules at import time —
the engine arrives as a constructor argument — so
`import paddle_tpu.serving` stays cheap and cycle-free.
"""
from __future__ import annotations

from . import (  # noqa: F401
    client, faults, fleet, handoff, kvcache, kvtier, metrics, replica,
    router, scheduler, server, timeline, wire,
)
from .client import ServingClient, ServingHTTPError  # noqa: F401
from .faults import FaultPlan, InjectedFault  # noqa: F401
from .fleet import (  # noqa: F401
    FleetPages, FleetPlane, FleetWorker, RemoteHandoffRef, RemoteReplica,
    RemoteRequest, connect_fleet, spawn_worker,
)
from .handoff import KVHandoff  # noqa: F401
from .kvcache import PagePool, PrefixCache  # noqa: F401
from .kvtier import HostTier  # noqa: F401
from .metrics import (  # noqa: F401
    Counter, EngineMetrics, Gauge, Histogram, MetricsRegistry,
)
from .replica import (  # noqa: F401
    Replica, ReplicaKilledError, build_replicas,
)
from .router import Router, RouterRequest, prefix_key  # noqa: F401
from .scheduler import (  # noqa: F401
    BackpressureError, CrashLoopError, DeadlineExceededError,
    PoisonedRequestError, RequestScheduler, SchedulerClosedError,
    SchedulerError, ServingRequest,
)
from .server import ServingServer  # noqa: F401
from .timeline import (  # noqa: F401
    StepAnomalySentinel, Timeline, judge_slo, resolve_slo, slo_targets,
)
from .wire import WireError  # noqa: F401

__all__ = [
    "client", "faults", "fleet", "handoff", "kvcache", "kvtier",
    "metrics", "replica", "router", "scheduler", "server", "timeline",
    "wire",
    "Timeline", "StepAnomalySentinel",
    "resolve_slo", "slo_targets", "judge_slo",
    "ServingClient", "ServingHTTPError",
    "FaultPlan", "InjectedFault", "KVHandoff",
    "PagePool", "PrefixCache", "HostTier",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "EngineMetrics",
    "Replica", "ReplicaKilledError", "build_replicas",
    "Router", "RouterRequest", "prefix_key",
    "RequestScheduler", "ServingRequest", "SchedulerError",
    "BackpressureError", "DeadlineExceededError", "SchedulerClosedError",
    "PoisonedRequestError", "CrashLoopError",
    "ServingServer",
    "WireError", "FleetWorker", "FleetPages", "FleetPlane",
    "RemoteReplica", "RemoteRequest", "RemoteHandoffRef",
    "connect_fleet", "spawn_worker",
]
