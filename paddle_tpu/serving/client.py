"""Stdlib HTTP client for the serving frontend (`serving/server.py`).

Token-id in, token-id out — the wire protocol is tokenizer-free, like
the server. Streaming completions iterate Server-Sent-Events as the
engine emits chunks; everything else is one JSON round trip. Responses
carry an OpenAI-style `usage` block (`prompt_tokens`,
`completion_tokens`, `cached_tokens` — the prompt prefix the server's
KV cache served without prefill compute).

Backpressure: a full server queue is HTTP 429 with `Retry-After`
(`BackpressureError.retry_after_s` on the server side). With
`retries=N` (opt-in; default 0 preserves raise-immediately) the client
honors that hint — bounded retries with jittered sleeps — before
surfacing `ServingHTTPError`.

The same bounded budget also retries **connection-level** failures —
refused, reset, or dropped before any response byte arrived
(`ConnectionError`, `http.client.RemoteDisconnected`) — so a rolling
replica restart behind the router is invisible to callers. This is
safe for this protocol because a completion request is idempotent
(deterministic generation for the given parameters) and streams are
only ever retried before the first streamed byte.
"""
from __future__ import annotations

import http.client
import json
import random
import time

__all__ = ["ServingClient", "ServingHTTPError"]

# connection-level failures worth retrying: the server never saw the
# request (refused) or dropped it before responding (reset / remote
# disconnected during a restart). ConnectionError covers Refused,
# Reset, Aborted, and BrokenPipe.
_CONN_ERRORS = (ConnectionError, http.client.RemoteDisconnected)


class ServingHTTPError(RuntimeError):
    """Non-2xx response; carries the status, decoded body, and (for
    429) the server's Retry-After hint in seconds."""

    def __init__(self, status, body, retry_after_s=None):
        self.status = status
        self.body = body
        self.retry_after_s = retry_after_s
        msg = body.get("error", body) if isinstance(body, dict) else body
        super().__init__(f"HTTP {status}: {msg}")

    @property
    def retriable(self):
        return self.status in (429, 503)


def _retry_after(resp):
    try:
        v = resp.getheader("Retry-After")
        return float(v) if v is not None else None
    except (TypeError, ValueError):
        return None


class ServingClient:
    def __init__(self, host="127.0.0.1", port=8000, timeout=120.0,
                 retries=0, retry_cap_s=5.0, _rng=None):
        self.host = host
        self.port = port
        self.timeout = timeout
        # opt-in bounded retry on 429 backpressure and Retry-After-
        # carrying 503s (crash-loop breaker); never on bare-503
        # shutdown or 4xx request errors — those don't heal by waiting
        self.retries = int(retries)
        self.retry_cap_s = float(retry_cap_s)
        self._rng = _rng if _rng is not None else random.Random()

    def _request(self, method, path, body=None):
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        headers = {}
        payload = None
        if body is not None:
            payload = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        conn.request(method, path, body=payload, headers=headers)
        return conn, conn.getresponse()

    def _with_retries(self, fn):
        """Run fn(); retry (at most `self.retries` extra times) on 429
        backpressure and on 503s that carry Retry-After (the crash-
        loop breaker: the replica heals on revive, so a single-replica
        deployment is retried instead of surfaced) — sleeping out the
        server's hint, capped and jittered to decorrelate a thundering
        herd — and on connection refused/reset/disconnect with a short
        exponential backoff (a replica restarting behind the router).
        A bare 503 (draining shutdown) and everything else raise
        immediately — those don't heal by waiting."""
        attempt = 0
        while True:
            try:
                return fn()
            except ServingHTTPError as e:
                healing = e.status == 429 or (
                    e.status == 503 and e.retry_after_s is not None)
                if not healing or attempt >= self.retries:
                    raise
                hint = e.retry_after_s if e.retry_after_s is not None \
                    else 1.0
                time.sleep(min(hint, self.retry_cap_s)
                           * (0.5 + self._rng.random()))
                attempt += 1
            except _CONN_ERRORS:
                if attempt >= self.retries:
                    raise
                time.sleep(min(0.05 * (2 ** attempt), self.retry_cap_s)
                           * (0.5 + self._rng.random()))
                attempt += 1

    def _json_call(self, method, path, body=None):
        conn, resp = self._request(method, path, body)
        try:
            data = resp.read()
            try:
                decoded = json.loads(data)
            except json.JSONDecodeError:
                decoded = data.decode(errors="replace")
            if resp.status >= 400:
                raise ServingHTTPError(resp.status, decoded,
                                       retry_after_s=_retry_after(resp))
            return decoded
        finally:
            conn.close()

    # -- endpoints ----------------------------------------------------
    def healthz(self):
        return self._json_call("GET", "/healthz")

    def readyz(self):
        """Readiness probe; raises ServingHTTPError(503) while the
        server is paused or draining (liveness stays 200)."""
        return self._json_call("GET", "/readyz")

    def metrics(self):
        """JSON snapshot of the server's metrics registry."""
        return self._json_call("GET", "/metrics?format=json")

    def debug_requests(self, last=50):
        """Recent terminal requests with their stitched timelines
        (/debug/requests?last=N); behind a router each entry carries
        its `replica` tag."""
        return self._json_call("GET", f"/debug/requests?last={int(last)}")

    def debug_pulse(self, window=None, signals=None):
        """The pulse plane's ring time-series (/debug/pulse): windowed
        to the last `window` seconds, filtered to signal-name prefixes
        in `signals`; behind a router one payload per replica."""
        q = []
        if window is not None:
            q.append(f"window={int(window)}")
        if signals:
            q.append("signals=" + ",".join(signals))
        return self._json_call(
            "GET", "/debug/pulse" + ("?" + "&".join(q) if q else ""))

    def metrics_text(self):
        """Prometheus text exposition."""
        conn, resp = self._request("GET", "/metrics")
        try:
            body = resp.read().decode()
            if resp.status >= 400:
                raise ServingHTTPError(resp.status, body)
            return body
        finally:
            conn.close()

    def complete(self, prompt_ids, **params):
        """Blocking completion; returns the response dict
        ({"tokens": [...], "state": ..., "usage": {...}, ...})."""
        body = dict(params, prompt=list(map(int, prompt_ids)),
                    stream=False)
        return self._with_retries(
            lambda: self._json_call("POST", "/v1/completions", body))

    def stream_complete(self, prompt_ids, **params):
        """Generator of SSE event dicts: token chunks as
        {"tokens": [...]}, then a final {"done": true, ...} event
        carrying the usage block. 429 retries happen before the first
        byte is yielded (a stream, once started, is never replayed)."""
        body = dict(params, prompt=list(map(int, prompt_ids)),
                    stream=True)

        def _open():
            conn, resp = self._request("POST", "/v1/completions", body)
            if resp.status >= 400:
                try:
                    data = resp.read()
                    try:
                        decoded = json.loads(data)
                    except json.JSONDecodeError:
                        decoded = data.decode(errors="replace")
                    raise ServingHTTPError(
                        resp.status, decoded,
                        retry_after_s=_retry_after(resp))
                finally:
                    conn.close()
            return conn, resp

        conn, resp = self._with_retries(_open)
        try:
            # http.client undoes the chunked framing; reassemble SSE
            # events (data: <json>\n\n) line by line
            for line in resp:
                line = line.strip()
                if line.startswith(b"data: "):
                    yield json.loads(line[len(b"data: "):])
        finally:
            conn.close()
