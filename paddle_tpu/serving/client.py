"""Stdlib HTTP client for the serving frontend (`serving/server.py`).

Token-id in, token-id out — the wire protocol is tokenizer-free, like
the server. Streaming completions iterate Server-Sent-Events as the
engine emits chunks; everything else is one JSON round trip.
"""
from __future__ import annotations

import http.client
import json

__all__ = ["ServingClient", "ServingHTTPError"]


class ServingHTTPError(RuntimeError):
    """Non-2xx response; carries the status and decoded body."""

    def __init__(self, status, body):
        self.status = status
        self.body = body
        msg = body.get("error", body) if isinstance(body, dict) else body
        super().__init__(f"HTTP {status}: {msg}")

    @property
    def retriable(self):
        return self.status in (429, 503)


class ServingClient:
    def __init__(self, host="127.0.0.1", port=8000, timeout=120.0):
        self.host = host
        self.port = port
        self.timeout = timeout

    def _request(self, method, path, body=None):
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        headers = {}
        payload = None
        if body is not None:
            payload = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        conn.request(method, path, body=payload, headers=headers)
        return conn, conn.getresponse()

    def _json_call(self, method, path, body=None):
        conn, resp = self._request(method, path, body)
        try:
            data = resp.read()
            try:
                decoded = json.loads(data)
            except json.JSONDecodeError:
                decoded = data.decode(errors="replace")
            if resp.status >= 400:
                raise ServingHTTPError(resp.status, decoded)
            return decoded
        finally:
            conn.close()

    # -- endpoints ----------------------------------------------------
    def healthz(self):
        return self._json_call("GET", "/healthz")

    def metrics(self):
        """JSON snapshot of the server's metrics registry."""
        return self._json_call("GET", "/metrics?format=json")

    def metrics_text(self):
        """Prometheus text exposition."""
        conn, resp = self._request("GET", "/metrics")
        try:
            body = resp.read().decode()
            if resp.status >= 400:
                raise ServingHTTPError(resp.status, body)
            return body
        finally:
            conn.close()

    def complete(self, prompt_ids, **params):
        """Blocking completion; returns the response dict
        ({"tokens": [...], "state": ..., ...})."""
        body = dict(params, prompt=list(map(int, prompt_ids)),
                    stream=False)
        return self._json_call("POST", "/v1/completions", body)

    def stream_complete(self, prompt_ids, **params):
        """Generator of SSE event dicts: token chunks as
        {"tokens": [...]}, then a final {"done": true, ...} event."""
        body = dict(params, prompt=list(map(int, prompt_ids)),
                    stream=True)
        conn, resp = self._request("POST", "/v1/completions", body)
        try:
            if resp.status >= 400:
                data = resp.read()
                try:
                    decoded = json.loads(data)
                except json.JSONDecodeError:
                    decoded = data.decode(errors="replace")
                raise ServingHTTPError(resp.status, decoded)
            # http.client undoes the chunked framing; reassemble SSE
            # events (data: <json>\n\n) line by line
            for line in resp:
                line = line.strip()
                if line.startswith(b"data: "):
                    yield json.loads(line[len(b"data: "):])
        finally:
            conn.close()
