"""Deterministic fault injection for the serving stack.

Chaos drills against a serving runtime are only evidence when they are
*replayable*: "we killed a replica once and it looked fine" proves
nothing about the crash window that matters. A `FaultPlan` is a seeded,
declarative schedule of failures registered at the stack's real
failure sites — the places a device loss, a bad DMA, or a poisoned
input would actually surface:

  ==================  ====================================================
  point               fires at
  ==================  ====================================================
  step_launch         the decode/verify device dispatch
                      (`ServingEngine.step_launch` / `_spec_step`)
  step_finish         the async result read of a launched step
                      (`step_finish` / the spec fetch)
  suffix_prefill      a prefix-cache hit's suffix-only prefill dispatch
  tier_spill          the host tier's device->host page copy
                      (`HostTier._land`, on the copy thread)
  tier_restore        the tier's host->device restore scatter
                      (`ServingEngine._tier_restore`)
  router_dispatch     `Router.submit`, before replica selection
  handoff_export      the disaggregated-serving KV page export
                      (`HostTier.export_pages`, on the copy thread)
  handoff_import      the decode replica's KV handoff import scatter
                      (`ServingEngine._import_handoff`, before alloc)
  ==================  ====================================================

Each rule arms one point with an action — ``raise`` (an
`InjectedFault`, or a caller-supplied exception), ``delay`` (a sleep,
for timeout/overlap drills), or ``corrupt`` (a deterministic byte flip
of the payload flowing through the point, where one is plumbed) — on
the Nth matching hit, optionally for a run of hits, optionally only
when a named request id is in the batch. Hit counters are per-rule and
advance deterministically with the engine's own step count, so a drill
replays byte-for-byte from the same spec + workload.

Plans come from the ``PT_FAULTS`` environment variable or a
constructor argument. The grammar (documented in docs/reliability.md):

    PT_FAULTS="step_launch:raise@4;tier_spill:raise@1"
    rule   := point ":" action "@" first ["x" (count | "*")] [":" args]
    args   := key "=" value ("," key "=" value)*   # delay=, rid=, msg=
    spec   := (rule | "seed=" int) (";" rule)*

`Replica.kill()` is just one plan among many: it adds an infinite
``step_launch:raise`` rule and `revive()` removes it.

Pure stdlib + numpy; no jax, no model imports — the plan can be built
anywhere (tests, bench, ops tooling) and attached to an engine, tier,
or router.
"""
from __future__ import annotations

import threading
import time

from .._env import env_str
from ..observability import flight_recorder as _flight

__all__ = ["FaultPlan", "InjectedFault", "POINTS", "ACTIONS"]

POINTS = ("step_launch", "step_finish", "suffix_prefill", "tier_spill",
          "tier_restore", "router_dispatch", "handoff_export",
          "handoff_import")
ACTIONS = ("raise", "delay", "corrupt")


class InjectedFault(RuntimeError):
    """A FaultPlan rule fired with action=raise. Carries the point and
    the hit number so a recovery path (or a test) can tell injected
    failures from organic ones."""

    def __init__(self, point, hit, msg=None):
        self.point = point
        self.hit = hit
        super().__init__(
            msg or f"injected fault at {point} (hit {hit})")


class _Rule:
    __slots__ = ("point", "action", "first", "count", "delay_s", "exc",
                 "msg", "rid", "label", "matched", "fired")

    def __init__(self, point, action, first, count, delay_s, exc, msg,
                 rid, label):
        if point not in POINTS:
            raise ValueError(
                f"faults: unknown point {point!r}; want one of {POINTS}")
        if action not in ACTIONS:
            raise ValueError(
                f"faults: unknown action {action!r}; want one of {ACTIONS}")
        if first < 1:
            raise ValueError(f"faults: first={first}: hits are 1-based")
        if count is not None and count < 1:
            raise ValueError(f"faults: count={count}: want >= 1 or None")
        self.point = point
        self.action = action
        self.first = int(first)
        self.count = None if count is None else int(count)
        self.delay_s = float(delay_s)
        self.exc = exc
        self.msg = msg
        self.rid = rid
        self.label = label
        self.matched = 0            # matching fire() calls seen
        self.fired = 0              # times the action actually ran

    def describe(self):
        span = "*" if self.count is None else str(self.count)
        rid = f":rid={self.rid}" if self.rid is not None else ""
        return f"{self.point}:{self.action}@{self.first}x{span}{rid}"


class FaultPlan:
    """A seeded schedule of injected failures (module doc has the
    grammar and the point registry). Thread-safe: fire() is called from
    the pump thread, the tier's copy thread, and HTTP threads; the
    actions themselves (sleep / raise) run outside the lock."""

    def __init__(self, spec="", seed=0):
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._rules = []
        self.hits = {}              # point -> fire() calls, for drills
        self.fired = []             # (point, hit, action, label) log
        if spec:
            self._parse(spec)

    @classmethod
    def from_env(cls, env=None):
        """Plan from ``PT_FAULTS`` (None when unset/empty — the
        disabled default costs nothing and preserves seed behavior
        exactly)."""
        spec = env_str("PT_FAULTS", env=env)
        return cls(spec) if spec else None

    # -- construction --------------------------------------------------
    def _parse(self, spec):
        for seg in str(spec).split(";"):
            seg = seg.strip()
            if not seg:
                continue
            if seg.startswith("seed="):
                self.seed = int(seg[len("seed="):])
                continue
            head, at, rest = seg.partition("@")
            if not at:
                raise ValueError(
                    f"faults: rule {seg!r} has no '@first' clause")
            point, colon, action = head.partition(":")
            if not colon:
                raise ValueError(
                    f"faults: rule {seg!r} wants point:action@first")
            nth, _, args = rest.partition(":")
            first, x, cnt = nth.partition("x")
            count = 1 if not x else (None if cnt == "*" else int(cnt))
            kw = {}
            for pair in args.split(","):
                if not pair:
                    continue
                k, eq, v = pair.partition("=")
                if not eq:
                    raise ValueError(
                        f"faults: rule {seg!r}: arg {pair!r} wants k=v")
                kw[k] = v
            delay_s = float(kw.pop("delay", 0.01))
            rid = kw.pop("rid", None)
            msg = kw.pop("msg", None)
            if kw:
                raise ValueError(
                    f"faults: rule {seg!r}: unknown args {sorted(kw)}")
            self.add(point.strip(), action.strip(), first=int(first),
                     count=count, delay_s=delay_s, rid=rid, msg=msg)

    def add(self, point, action, *, first=1, count=1, delay_s=0.01,
            exc=None, msg=None, rid=None, label=None):
        """Arm one rule; returns it. `count=None` = every matching hit
        from `first` on (how `Replica.kill` models a dead engine)."""
        rule = _Rule(point, action, first, count, delay_s, exc, msg,
                     rid, label)
        with self._lock:
            self._rules.append(rule)
        return rule

    def remove(self, label):
        """Drop every rule carrying `label` (Replica.revive)."""
        with self._lock:
            self._rules = [r for r in self._rules if r.label != label]

    # -- injection -----------------------------------------------------
    def fire(self, point, value=None, rids=None):
        """One hit at `point`. Counts the hit, runs any armed actions
        (raise / sleep / corrupt), and returns `value` (possibly
        corrupted). `rids` is the request ids at the point, for
        rid-scoped rules (poison-request drills)."""
        if point not in POINTS:
            raise ValueError(
                f"faults: unknown point {point!r}; want one of {POINTS}")
        due = []
        with self._lock:
            hit = self.hits.get(point, 0) + 1
            self.hits[point] = hit
            for rule in self._rules:
                if rule.point != point:
                    continue
                if rule.rid is not None and (
                        rids is None or rule.rid not in rids):
                    continue
                rule.matched += 1
                if rule.matched < rule.first:
                    continue
                if rule.count is not None and \
                        rule.matched >= rule.first + rule.count:
                    continue
                rule.fired += 1
                due.append(rule)
                self.fired.append((point, hit, rule.action, rule.label))
        for rule in due:
            _flight.record("fault.injected", point=point, hit=hit,
                           action=rule.action, label=rule.label,
                           rid=rule.rid)
            if rule.action == "delay":
                time.sleep(rule.delay_s)
            elif rule.action == "corrupt":
                value = self._corrupt(point, hit, value)
            else:  # raise
                raise rule.exc if rule.exc is not None else \
                    InjectedFault(point, hit, rule.msg)
        return value

    def _corrupt(self, point, hit, value):
        """Deterministic single-byte flip of an array payload — a
        seeded stand-in for a bad DMA. Non-array payloads (points with
        nothing plumbed) pass through untouched."""
        import numpy as np
        if value is None or not isinstance(value, np.ndarray) or \
                value.size == 0:
            return value
        a = np.array(value, copy=True)
        buf = a.view(np.uint8).reshape(-1)
        rs = np.random.RandomState(
            (self.seed * 1000003 + hit * 9176 + len(point)) % (2**31 - 1))
        buf[int(rs.randint(0, buf.size))] ^= 0xFF
        return a

    # -- introspection -------------------------------------------------
    def stats(self):
        with self._lock:
            return {
                "seed": self.seed,
                "hits": dict(self.hits),
                "fired": len(self.fired),
                "rules": [{"rule": r.describe(), "matched": r.matched,
                           "fired": r.fired, "label": r.label}
                          for r in self._rules],
            }

    def __repr__(self):
        with self._lock:
            rules = ";".join(r.describe() for r in self._rules)
        return f"FaultPlan({rules!r}, seed={self.seed})"
