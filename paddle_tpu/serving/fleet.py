"""Fleet plane: multi-host disaggregated serving over the rpc layer.

The serving stack below this module is a complete single-host runtime
— role-specialized replicas, KV handoff, SLO/pulse observability — but
every replica lives in the router's process. This module fronts
replicas running in OTHER processes (other hosts) behind the exact
same `Replica` duck-type, so `Router` gains multi-host disaggregation
with zero structural changes:

  * `FleetWorker` — the worker-process entrypoint. Wraps one local
    `Replica` behind an rpc-served endpoint (submit / stats / load /
    pause / resume / drain / kill / revive / recent_requests /
    metrics) on `distributed/rpc.py`'s named-worker control plane,
    plus a **bulk channel** (a dedicated TCP server speaking
    `serving/wire.py` frames — length-framed, chunked, no pickle for
    page payloads) that streams token frames back to the router and
    ships KV pages host-to-host. Registers in the `_TCPStore`
    rendezvous and beats a store-key heartbeat.
  * `RemoteReplica` — the router-side proxy satisfying the `Replica`
    duck-type. Requests come back as `RemoteRequest` handles that
    duck-type `ServingRequest` (stream/result/cancel, terminal
    states, `_streamed`), so failover, handoff migration and the SLO
    plane all work unchanged. Transport loss marks the replica dead
    and fails its in-flight requests exactly like an engine crash —
    the router's existing breaker/failover path takes over.
  * `KVHandoff` over the bulk channel — a prefill worker's exported
    pages stay put until the decode worker fetches them DIRECTLY from
    the source's bulk endpoint (`RemoteHandoffRef`): the router moves
    a ~100-byte reference, the pages move host-to-host once.
  * `FleetPages` — the kvtier multi-host follow-on: budget-evicted
    prefix pages spill to the peer that the consistent-hash prefix
    affinity names as owner (a DETERMINISTIC ring — the router's
    in-process ring hashes strings, which Python salts per process),
    and a short local match fetches missing chain blocks back from
    the owner. The fleet becomes one global prefix cache:
    `pt_fleet_spill_pages_total`, fetch-on-miss through the same bulk
    channel.
  * `FleetPlane` / `connect_fleet` — router-side bring-up: hosts the
    rendezvous store, waits for every worker's registration, builds
    the `RemoteReplica` pool, and monitors heartbeats (a worker whose
    beat stalls past `PT_FLEET_HB_MISS_S` is marked dead).

Env knobs: `PT_FLEET_HB_S` (beat interval, default 0.5),
`PT_FLEET_HB_MISS_S` (liveness timeout, default 3),
`PT_FLEET_CALL_TIMEOUT_S` (control-plane call timeout, default 30),
`PT_FLEET_RETRIES` (idempotent-call retries, default 2),
`PT_FLEET_FETCH_TIMEOUT_S` (per-page fetch-on-miss budget, default 1),
`PT_FLEET_FETCH_MAX` (blocks fetched per match, default 8).

Trust model is inherited from `distributed/rpc.py`: the control plane
is pickle over a trusted network. The bulk channel never unpickles —
JSON control frames + raw array bytes only — but it authenticates
nothing; run the fleet on a private interconnect (docs/serving.md
§ Fleet plane).

Worker processes launch via ``python -m paddle_tpu.serving.fleet
--spec '<json>'`` (see `spawn_worker`); the model/engine imports
happen inside that entrypoint, so this module keeps the serving
package's import-cycle-free contract.
"""
from __future__ import annotations

import hashlib
import json
import os
import queue
import socket
import sys
import threading
import time
from collections import OrderedDict

from .._env import env_float, env_int, env_str
from ..distributed import rpc as _rpc
from ..observability import fleet_obs as _fobs
from ..observability import flight_recorder as _flight
from ..observability import trace_context as _tc
from . import wire as _wire
from .kvcache import block_hash as _block_hash
from .metrics import MetricsRegistry
from .replica import ReplicaKilledError
from .scheduler import (BackpressureError, CrashLoopError,
                        DeadlineExceededError, PoisonedRequestError,
                        SchedulerClosedError, SchedulerError)
from .timeline import Timeline

__all__ = ["FleetWorker", "FleetPages", "FleetPlane", "RemoteReplica",
           "RemoteRequest", "RemoteHandoffRef", "connect_fleet",
           "spawn_worker", "ROUTER_NAME"]

# rank 0 of the fleet's rpc world is always the router process
ROUTER_NAME = "router"


# ---------------------------------------------------------------------------
# rpc endpoints: module-level functions so pickle ships them by
# REFERENCE (the worker resolves `paddle_tpu.serving.fleet._rpc_*`
# against its own import of this module). Every worker in a process
# registers in _WORKERS under its fleet name.

_WORKERS = {}


def _worker(name):
    w = _WORKERS.get(name)
    if w is None:
        raise RuntimeError(f"fleet: no worker {name!r} in this process "
                           f"(have {sorted(_WORKERS)})")
    return w


def _rpc_submit(name, prompt_ids, params):
    return _worker(name).handle_submit(prompt_ids, params)


def _rpc_cancel(name, rid):
    return _worker(name).handle_cancel(rid)


def _rpc_stats(name):
    return _worker(name).replica.stats()


def _rpc_load(name):
    return _worker(name).replica.load()


def _rpc_ready(name):
    return _worker(name).replica.ready()


def _rpc_recent_requests(name, n):
    return _worker(name).replica.recent_requests(n)


def _rpc_pause(name):
    _worker(name).replica.pause()
    return True


def _rpc_resume(name):
    _worker(name).replica.resume()
    return True


def _rpc_drain(name, timeout):
    return _worker(name).replica.drain(timeout=timeout)


def _rpc_shutdown(name, drain, timeout):
    return _worker(name).shutdown(drain=drain, timeout=timeout)


def _rpc_kill(name):
    _worker(name).replica.kill()
    return True


def _rpc_revive(name):
    _worker(name).replica.revive()
    return True


def _rpc_render_prometheus(name):
    return _worker(name).replica.scheduler.render_prometheus()


def _rpc_metrics_snapshot(name):
    return _worker(name).replica.scheduler.metrics_snapshot()


def _rpc_pulse(name, window, signals):
    sched = _worker(name).replica.scheduler
    if hasattr(sched, "pulse"):
        return sched.pulse(window=window, signals=signals)
    return {"enabled": False}


def _rpc_obs_snapshot(name, window):
    return _worker(name).obs_snapshot(window)


def _rpc_obs_triggers(name):
    return _worker(name).obs_triggers()


# ---------------------------------------------------------------------------
# bulk-channel clients (stdlib socket + serving/wire framing)


def _bulk_connect(addr, timeout):
    s = socket.create_connection(tuple(addr), timeout=timeout)
    s.settimeout(timeout)
    return s


def _fetch_handoff(addr, rid, timeout=None, acct=None):
    """Pull one exported KVHandoff from a worker's bulk endpoint —
    the host-to-host half of a decode migration."""
    timeout = timeout if timeout is not None \
        else env_float("PT_FLEET_CALL_TIMEOUT_S")
    acct = acct if acct is not None else _wire.WireAccount()
    t0 = time.perf_counter()
    with _bulk_connect(addr, timeout) as s:
        _wire.send_json(s, {"op": "handoff", "rid": str(rid)}, acct=acct)
        head = _wire.recv_json(s, acct=acct)
        if not head.get("ok"):
            raise _wire.WireError(
                f"fleet: worker holds no handoff for rid {rid!r}")
        h = _wire.recv_handoff(s, acct=acct)
    _tc.record_span_event(
        "wire.handoff_fetch", time.perf_counter() - t0,
        args={"rid": str(rid), "bytes": acct.rx_bytes + acct.tx_bytes,
              "frames": acct.frames})
    return h


def _push_handoff(addr, h, timeout=None, acct=None):
    """Push a locally-held KVHandoff to a worker's bulk endpoint (the
    local-replica -> remote-replica migration direction). Returns the
    payload bytes framed."""
    timeout = timeout if timeout is not None \
        else env_float("PT_FLEET_CALL_TIMEOUT_S")
    acct = acct if acct is not None else _wire.WireAccount()
    t0 = time.perf_counter()
    with _bulk_connect(addr, timeout) as s:
        _wire.send_json(s, {"op": "handoff_put"}, acct=acct)
        n = _wire.send_handoff(s, h, acct=acct)
        ack = _wire.recv_json(s, acct=acct)
        if not ack.get("ok"):
            raise _wire.WireError("fleet: handoff_put refused")
    _tc.record_span_event(
        "wire.handoff_push", time.perf_counter() - t0,
        args={"rid": str(getattr(h, "rid", "")),
              "bytes": acct.rx_bytes + acct.tx_bytes,
              "frames": acct.frames})
    return n


def _fetch_page(addr, key, timeout, acct=None):
    """Fetch one spilled prefix page by chained hash from its owner.
    Returns {parent, block, depth, payload} or None on a clean miss."""
    acct = acct if acct is not None else _wire.WireAccount()
    t0 = time.perf_counter()
    with _bulk_connect(addr, timeout) as s:
        _wire.send_json(s, {"op": "page_get", "key": int(key)},
                        acct=acct)
        head = _wire.recv_json(s, acct=acct)
        if not head.get("ok"):
            return None
        payload = {"k": _wire.recv_array(s, acct=acct),
                   "v": _wire.recv_array(s, acct=acct),
                   "ks": _wire.recv_array(s, acct=acct),
                   "vs": _wire.recv_array(s, acct=acct)}
        _tc.record_span_event(
            "wire.page_fetch", time.perf_counter() - t0,
            args={"bytes": acct.rx_bytes + acct.tx_bytes,
                  "frames": acct.frames})
        return {"parent": int(head["parent"]),
                "block": tuple(int(t) for t in head["block"]),
                "depth": int(head["depth"]), "payload": payload}


def _push_page(addr, parent, block, depth, payload, timeout,
               acct=None):
    """Ship one evicted prefix page to its owning peer. Returns bytes
    framed."""
    acct = acct if acct is not None else _wire.WireAccount()
    t0 = time.perf_counter()
    with _bulk_connect(addr, timeout) as s:
        _wire.send_json(s, {"op": "page_put", "parent": int(parent),
                            "block": [int(t) for t in block],
                            "depth": int(depth)}, acct=acct)
        n = 0
        for part in ("k", "v", "ks", "vs"):
            n += _wire.send_array(s, payload.get(part), acct=acct)
        ack = _wire.recv_json(s, acct=acct)
        if not ack.get("ok"):
            raise _wire.WireError("fleet: page_put refused")
    _tc.record_span_event(
        "wire.page_spill", time.perf_counter() - t0,
        args={"bytes": acct.rx_bytes + acct.tx_bytes,
              "frames": acct.frames})
    return n


class RemoteHandoffRef:
    """A KVHandoff that still lives on its exporting worker. Carries
    the flight-record metadata (`nbytes`/`pages`) so `Router._migrate`
    needs no change; resolves lazily into the real payload on first
    field access — which only happens when a LOCAL replica imports it
    (remote targets receive the reference and fetch source-direct)."""

    def __init__(self, addr, rid, nbytes=0, pages=0):
        self.addr = tuple(addr)
        self.rid = str(rid)
        self.nbytes = int(nbytes)
        self.pages = int(pages)
        self._payload = None
        self._rlock = threading.Lock()

    def resolve(self):
        with self._rlock:
            if self._payload is None:
                # _rlock's entire job is making concurrent resolvers
                # wait for the ONE bulk fetch instead of issuing N;
                # nothing else is ever guarded by it
                # tpulint: disable-next-line=TPL009 -- fetch-once dedupe: waiting on the in-flight fetch IS the lock's purpose
                self._payload = _fetch_handoff(self.addr, self.rid)
            return self._payload

    def __getattr__(self, name):
        # only fields NOT set in __init__ land here: the KVHandoff
        # surface (k/v/ks/vs/output/next_token/length/...)
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.resolve(), name)

    def __repr__(self):
        return (f"RemoteHandoffRef(addr={self.addr}, rid={self.rid!r}, "
                f"nbytes={self.nbytes}, pages={self.pages})")


# ---------------------------------------------------------------------------
# global prefix-page cache (worker side)


def _ring_point(s):
    """Deterministic 64-bit signed ring point. The router's in-process
    `_HashRing` uses `hash()` on strings — salted per process, fine
    for routing, useless for cross-host ownership agreement. blake2b
    gives every worker the identical ring."""
    d = hashlib.blake2b(s.encode(), digest_size=8).digest()
    v = int.from_bytes(d, "little")
    return v - (1 << 64) if v >= (1 << 63) else v


class FleetPages:
    """Multi-host prefix-page exchange over one worker's `HostTier`.

    Spill: the tier's budget evictions (`on_drop`, invoked outside the
    tier lock) enqueue to a bounded queue; a pump thread ships each
    page to the peer the deterministic consistent-hash ring names as
    the key's owner — the same replica the router's prefix affinity
    sends that prefix's PROMPTS to, so pages land where their hits
    are. Fetch: a local tier match that ends short of the prompt's cap
    asks the owner for the missing chain blocks (`fetch_missing`,
    bounded by PT_FLEET_FETCH_MAX pages and PT_FLEET_FETCH_TIMEOUT_S
    each), verifies (parent, block) raw, and inserts them locally.
    Peer-originated entries are flagged so budget pressure drops them
    without re-spilling (no ping-pong).
    """

    def __init__(self, worker):
        self.worker = worker
        self.tier = worker.replica.engine.host_tier
        self._self_rid = worker.replica.replica_id
        self._points = None          # built lazily: sorted [(pt, rid)]
        self._peers = {}             # replica_id -> meta dict
        self._ring_lock = threading.Lock()
        self._q = queue.Queue(maxsize=env_int("PT_FLEET_SPILL_QUEUE"))
        self._stop = threading.Event()
        self._thread = None
        r = worker.replica.registry
        self.spill_pages = r.counter(
            "pt_fleet_spill_pages",
            "Evicted prefix pages shipped to their owning peer.")
        self.spill_bytes = r.counter(
            "pt_fleet_spill_bytes",
            "Bytes of prefix pages shipped to peers.")
        self.spill_drops = r.counter(
            "pt_fleet_spill_drops",
            "Evicted pages NOT shipped (queue full, peer unreachable, "
            "or self-owned).")
        self.fetch_pages = r.counter(
            "pt_fleet_fetch_pages",
            "Prefix pages fetched from a peer on a local tier miss.")
        self.fetch_misses = r.counter(
            "pt_fleet_fetch_misses",
            "Fetch-on-miss attempts that found no page at the owner.")
        self.recv_pages = r.counter(
            "pt_fleet_recv_pages",
            "Prefix pages landed here by a peer's spill.")
        self.page_serves = r.counter(
            "pt_fleet_page_serves",
            "Spilled pages served to a fetching peer.")
        self.tier.on_drop = self.on_drop
        self.tier.fetch_missing = self.fetch_missing

    # -- ring ----------------------------------------------------------
    def _ensure_ring(self):
        with self._ring_lock:
            if self._points is not None:
                return self._points, dict(self._peers)
        # Build OUTSIDE the lock: membership is a store/rpc round trip
        # per peer, and holding _ring_lock across the network would
        # stall the spill loop and every owner_of() caller on one slow
        # peer. Racing builders each fetch an equivalent snapshot; the
        # first to publish wins and the rest discard theirs.
        agent = self.worker.agent
        peers = {}
        for info in agent.all_worker_infos():
            if info.rank == 0:
                continue             # the router owns no pages
            meta = self.worker.store.get(f"fleet/meta/{info.name}")
            peers[meta["replica_id"]] = meta
        pts = []
        for rid, meta in peers.items():
            # ring membership mirrors the router's: only replicas
            # that take NEW prompts own prefix keys
            if meta["role"] not in ("prefill", "both"):
                continue
            for i in range(64):
                pts.append((_ring_point(f"{rid}|{i}"), rid))
        pts.sort()
        with self._ring_lock:
            if self._points is None:
                self._points = pts
                self._peers = peers
            return self._points, dict(self._peers)

    def owner_of(self, key):
        pts, _ = self._ensure_ring()
        if not pts:
            return None
        import bisect
        i = bisect.bisect_left(pts, (int(key),))
        return pts[i % len(pts)][1]

    # -- spill side (tier copy/pump threads enqueue; pump ships) -------
    def on_drop(self, entries):
        """Tier hook: budget-evicted (key, entry) pairs, lock already
        released. Enqueue-or-drop — never block the calling thread."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._spill_loop, daemon=True,
                name=f"pt-fleet-spill-{self.worker.name}")
            self._thread.start()
        for key, e in entries:
            try:
                self._q.put_nowait((key, e))
            except queue.Full:
                self.spill_drops.inc()

    def _spill_loop(self):
        timeout = env_float("PT_FLEET_FETCH_TIMEOUT_S") * 5
        while not self._stop.is_set():
            try:
                key, e = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                owner = self.owner_of(key)
                if owner is None or owner == self._self_rid:
                    self.spill_drops.inc()
                    continue
                _, peers = self._ensure_ring()
                meta = peers.get(owner)
                if meta is None:
                    self.spill_drops.inc()
                    continue
                n = _push_page((meta["bulk_ip"], meta["bulk_port"]),
                               e["parent"], e["block"], e["depth"],
                               e["payload"], timeout,
                               acct=self.worker.wire_acct("bulk"))
                self.spill_pages.inc()
                self.spill_bytes.inc(n)
                _flight.record("fleet.spill", owner=owner, bytes=n,
                               depth=e["depth"])
            except Exception as err:  # noqa: BLE001 — a lost spill is a miss
                self.spill_drops.inc()
                _flight.record("fleet.spill_error", error=repr(err))
            finally:
                self._q.task_done()

    # -- fetch side (engine admission path, outside the tier lock) -----
    def fetch_missing(self, parent, block_idx, tokens):
        """Tier hook: the local chain walk ended at `block_idx` with
        chain hash `parent`; continue it through the owning peers.
        Returns chain-order payloads (possibly empty)."""
        ps = self.tier.page_size
        limit = (len(tokens) - 1) // ps
        budget = env_int("PT_FLEET_FETCH_MAX")
        timeout = env_float("PT_FLEET_FETCH_TIMEOUT_S")
        out = []
        b = int(block_idx)
        while b < limit and len(out) < budget:
            block = tuple(int(t) for t in tokens[b * ps:(b + 1) * ps])
            key = _block_hash(parent, block)
            owner = self.owner_of(key)
            if owner is None or owner == self._self_rid:
                break                # a local miss IS the answer here
            _, peers = self._ensure_ring()
            meta = peers.get(owner)
            if meta is None:
                break
            try:
                entry = _fetch_page((meta["bulk_ip"], meta["bulk_port"]),
                                    key, timeout,
                                    acct=self.worker.wire_acct("bulk"))
            except Exception:  # noqa: BLE001 — peer down == miss
                self.fetch_misses.inc()
                break
            if entry is None or entry["parent"] != parent \
                    or entry["block"] != block:
                self.fetch_misses.inc()
                break
            self.tier.insert(parent, block, b, entry["payload"],
                             fleet=True)
            out.append(entry["payload"])
            self.fetch_pages.inc()
            parent = key
            b += 1
        if out:
            _flight.record("fleet.fetch", pages=len(out))
        return out

    # -- serve side (bulk handler) -------------------------------------
    def serve_page(self, conn, key):
        e = self.tier.peek(int(key))
        acct = self.worker.wire_acct("bulk")
        if e is None:
            _wire.send_json(conn, {"ok": False}, acct=acct)
            return
        t0 = time.perf_counter()
        _wire.send_json(conn, {"ok": True, "parent": int(e["parent"]),
                               "block": [int(t) for t in e["block"]],
                               "depth": int(e["depth"])}, acct=acct)
        for part in ("k", "v", "ks", "vs"):
            _wire.send_array(conn, e["payload"].get(part), acct=acct)
        self.page_serves.inc()
        _tc.record_span_event(
            "wire.page_serve", time.perf_counter() - t0,
            args={"bytes": acct.tx_bytes, "frames": acct.frames,
                  "worker": self.worker.name})

    def land_page(self, conn, head):
        acct = self.worker.wire_acct("bulk")
        t0 = time.perf_counter()
        payload = {"k": _wire.recv_array(conn, acct=acct),
                   "v": _wire.recv_array(conn, acct=acct),
                   "ks": _wire.recv_array(conn, acct=acct),
                   "vs": _wire.recv_array(conn, acct=acct)}
        ok = self.tier.insert(
            int(head["parent"]),
            tuple(int(t) for t in head["block"]),
            int(head["depth"]), payload, fleet=True)
        if ok:
            self.recv_pages.inc()
        _wire.send_json(conn, {"ok": bool(ok)}, acct=acct)
        _tc.record_span_event(
            "wire.page_land", time.perf_counter() - t0,
            args={"bytes": acct.rx_bytes, "frames": acct.frames,
                  "worker": self.worker.name})

    def stop(self):
        self._stop.set()


# ---------------------------------------------------------------------------
# worker process


class FleetWorker:
    """One fleet member: a local `Replica` served over the rpc control
    plane plus a bulk channel for token streams and KV pages. See the
    module docstring for the topology; `run_worker`/`spawn_worker` for
    the process entrypoint. Multiple FleetWorkers may share a process
    (loopback tests drive the full wire path that way)."""

    def __init__(self, name, replica, *, master_endpoint, rank,
                 world_size, host=None, bulk_bind=None):
        self.name = str(name)
        self.replica = replica
        self.host = str(host or socket.gethostname())
        # the host tag rides the replica so every metric and /debug
        # payload the router aggregates carries host= next to replica=
        replica.host = self.host
        self._requests = {}          # rid -> live ServingRequest
        self._req_lock = threading.Lock()
        # exported handoffs kept for peer fetch (NOT popped on read: a
        # refused admission retries the fetch from the next candidate)
        self._handoffs = OrderedDict()
        # handoff payloads pushed TO this worker ahead of a submit
        self._kv_imports = {}
        self._stop = threading.Event()
        # heartbeat has its OWN stop: the heartbeat-loss drill silences
        # the beat while the worker keeps serving (a network partition
        # between worker and store, not a worker death)
        self._hb_stop = threading.Event()
        r = replica.registry
        self.stream_serves = r.counter(
            "pt_fleet_stream_serves",
            "Token streams served to the router over the bulk channel.")
        self.handoff_serves = r.counter(
            "pt_fleet_handoff_serves",
            "KV handoffs served to a fetching peer over the bulk "
            "channel.")
        self.handoff_wire_bytes = r.counter(
            "pt_fleet_handoff_wire_bytes",
            "KV handoff payload bytes actually framed onto the bulk "
            "socket.")
        self._wire_counters = {}     # chan -> (tx, rx, frames)
        _WORKERS[self.name] = self
        # every worker leaves evidence: the flight ring dumps on
        # SIGTERM/fault, and the router's fleet capture pulls the same
        # ring over rpc (install() is idempotent + thread-safe)
        _flight.install()

        # bulk channel first: its advertised endpoint rides the meta
        bind = bulk_bind or env_str("PT_RPC_BIND")
        self._bulk_srv = socket.create_server((bind, 0))
        self._bulk_srv.settimeout(0.2)
        ip, port = self._bulk_srv.getsockname()[:2]
        if ip in ("0.0.0.0", "::"):
            ip = _rpc._routable_ip()
        self.bulk_addr = (ip, int(port))
        self._bulk_thread = threading.Thread(
            target=self._bulk_serve, daemon=True,
            name=f"pt-fleet-bulk-{self.name}")
        self._bulk_thread.start()

        # rendezvous: meta is published BEFORE the agent barrier, so
        # once ANY worker's rendezvous completes every peer's meta is
        # readable without blocking
        mhost, mport = str(master_endpoint).rsplit(":", 1)
        self.store = _rpc._TCPStore(mhost, int(mport), False)
        self.store.set(f"fleet/meta/{self.name}", {
            "name": self.name,
            "replica_id": replica.replica_id,
            "role": replica.role,
            "host": self.host,
            "page_size": int(replica.page_size),
            "max_queue": int(replica.max_queue),
            "bulk_ip": ip, "bulk_port": int(port),
        })
        self.agent = _rpc.RpcAgent(self.name, int(rank), int(world_size),
                                   self.store)

        # heartbeat: a monotonically increasing store key — seq-based,
        # so router-side liveness needs no clock agreement
        self._hb_thread = threading.Thread(
            target=self._heartbeat, daemon=True,
            name=f"pt-fleet-hb-{self.name}")
        self._hb_thread.start()

        # global prefix cache rides the replica's host tier when one
        # is enabled
        tier = getattr(replica.engine, "host_tier", None)
        self.pages = FleetPages(self) \
            if tier is not None and tier.enabled else None
        _flight.record("fleet.worker_up", worker=self.name,
                       replica=replica.replica_id, host=self.host)

    # -- wire accounting -----------------------------------------------
    def wire_acct(self, chan):
        """A fresh per-transfer `WireAccount` bound to this worker's
        per-channel wire counters: the local tallies feed span byte
        counts, the bound counters feed the symmetric
        pt_wire_{tx,rx}_bytes / pt_wire_frames series the router
        surfaces per replica@host."""
        c = self._wire_counters.get(chan)
        if c is None:
            r = self.replica.registry
            c = (r.counter("pt_wire_tx_bytes",
                           "Bytes framed onto fleet sockets (header + "
                           "payload).", labels={"chan": chan}),
                 r.counter("pt_wire_rx_bytes",
                           "Bytes received off fleet sockets (header + "
                           "payload).", labels={"chan": chan}),
                 r.counter("pt_wire_frames",
                           "Frames moved over fleet sockets, both "
                           "directions.", labels={"chan": chan}))
            # benign race: the registry dedups by (name, labels), so
            # two threads landing here cache the same counter objects
            self._wire_counters[chan] = c
        return _wire.WireAccount(tx=c[0], rx=c[1], frames=c[2])

    # -- heartbeat -----------------------------------------------------
    def _heartbeat(self):
        interval = env_float("PT_FLEET_HB_S")
        seq = 0
        while not self._hb_stop.wait(0 if seq == 0 else interval):
            try:
                self.store.set(f"fleet/hb/{self.name}", seq)
            except (ConnectionError, OSError, TimeoutError):
                pass                 # master gone; shutdown will follow
            seq += 1

    def stop_heartbeat(self):
        """Test hook for the heartbeat-loss drill: the worker keeps
        serving but its beat goes silent, so the router must degrade
        it without dropping requests."""
        self._hb_stop.set()

    # -- bulk channel ---------------------------------------------------
    def _bulk_serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._bulk_srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._bulk_handle, args=(conn,),
                             daemon=True).start()
        try:
            self._bulk_srv.close()
        except OSError:
            pass

    def _bulk_handle(self, conn):
        try:
            with conn:
                head = _wire.recv_json(conn,
                                       acct=self.wire_acct("control"))
                op = head.get("op")
                if op == "stream":
                    self._serve_stream(conn, str(head.get("rid")))
                elif op == "handoff":
                    self._serve_handoff(conn, str(head.get("rid")))
                elif op == "handoff_put":
                    acct = self.wire_acct("bulk")
                    t0 = time.perf_counter()
                    h = _wire.recv_handoff(conn, acct=acct)
                    with self._req_lock:
                        self._kv_imports[str(h.rid)] = h
                    _wire.send_json(conn, {"ok": True}, acct=acct)
                    _tc.record_span_event(
                        "wire.handoff_land",
                        time.perf_counter() - t0,
                        args={"rid": str(h.rid),
                              "bytes": acct.rx_bytes,
                              "frames": acct.frames,
                              "worker": self.name})
                elif op == "page_put" and self.pages is not None:
                    self.pages.land_page(conn, head)
                elif op == "page_get" and self.pages is not None:
                    self.pages.serve_page(conn, head.get("key", 0))
                else:
                    _wire.send_json(conn, {"ok": False,
                                           "error": f"bad op {op!r}"})
        except (ConnectionError, OSError) as e:
            _flight.record("fleet.bulk_error", worker=self.name,
                           error=repr(e))

    def _serve_stream(self, conn, rid):
        """Forward one request's token chunks as JSON frames, then a
        terminal frame carrying everything the router-side handle
        mirrors (state, error, full output, stitched timeline, SLO
        verdict, handoff reference metadata)."""
        with self._req_lock:
            sr = self._requests.get(rid)
        if sr is None:
            _wire.send_json(conn, {"t": "end", "state": "failed",
                                   "error": {"type": "KeyError",
                                             "msg": f"no request {rid}"},
                                   "output": []})
            return
        self.stream_serves.inc()
        acct = self.wire_acct("stream")
        t0 = time.perf_counter()
        err = None
        try:
            for chunk in sr.stream():
                _wire.send_json(conn, {"t": "chunk",
                                       "toks": [int(t) for t in chunk]},
                                acct=acct)
        except Exception as e:  # noqa: BLE001 — shipped as the terminal error
            err = {"type": type(e).__name__, "msg": str(e)}
        h = sr.handoff
        frame = {
            "t": "end", "state": sr.state, "error": err,
            "output": [int(t) for t in sr.output],
            "logprobs": getattr(sr.req, "logprobs", None),
            "cached_tokens": int(getattr(sr.req, "cached_tokens", 0) or 0),
            "timeline": sr.timeline.to_dict()
            if sr.timeline is not None else None,
            "slo": sr.slo, "slo_attained": sr.slo_attained,
            "violated_phase": sr.violated_phase,
            "handoff": None if h is None else {
                "nbytes": int(h.nbytes), "pages": int(h.pages)},
        }
        if h is not None:
            with self._req_lock:
                self._handoffs[rid] = h
                while len(self._handoffs) > 64:
                    self._handoffs.popitem(last=False)
        with self._req_lock:
            self._requests.pop(rid, None)
        _wire.send_json(conn, frame, acct=acct)
        # worker half of the stream: same span name as the router's
        # reader half, so the stitched fleet trace shows the transfer
        # from both ends of the socket
        _tc.record_span_event(
            "wire.stream", time.perf_counter() - t0,
            trace_id=sr.trace_id,
            args={"rid": rid, "bytes": acct.tx_bytes,
                  "frames": acct.frames, "worker": self.name})

    def _serve_handoff(self, conn, rid):
        with self._req_lock:
            h = self._handoffs.get(rid)
        if h is None:
            _wire.send_json(conn, {"ok": False})
            return
        acct = self.wire_acct("bulk")
        t0 = time.perf_counter()
        _wire.send_json(conn, {"ok": True}, acct=acct)
        n = _wire.send_handoff(conn, h, acct=acct)
        dt = time.perf_counter() - t0
        self.handoff_serves.inc()
        self.handoff_wire_bytes.inc(n)
        # the socket hop lands in the same histogram the in-process
        # export path observes: pt_handoff_seconds measures time spent
        # MOVING handoffs, whichever transport carried them
        self.replica.registry.histogram(
            "pt_handoff_seconds",
            "Handoff export/transfer wall time.").observe(dt)
        _tc.record_span_event(
            "wire.handoff", dt,
            args={"rid": rid, "bytes": acct.tx_bytes,
                  "frames": acct.frames, "worker": self.name})
        _flight.record("fleet.handoff_serve", worker=self.name,
                       rid=rid, bytes=n, seconds=round(dt, 6))

    # -- rpc-facing handlers -------------------------------------------
    def handle_submit(self, prompt_ids, params):
        # the rpc layer binds the inbound trace meta around dispatch;
        # re-bind from params too so the in-process harness path (no
        # rpc hop) keeps the same worker-side trace identity
        tid = (params or {}).get("trace_id")
        if tid and _tc.current_trace_id() != tid:
            with _tc.bind(tid):
                return self._handle_submit(prompt_ids, params)
        return self._handle_submit(prompt_ids, params)

    def _handle_submit(self, prompt_ids, params):
        params = dict(params)
        ref = params.pop("kv_import_ref", None)
        token = params.pop("kv_import_token", None)
        kv_import = None
        if token is not None:
            with self._req_lock:
                kv_import = self._kv_imports.pop(str(token), None)
            if kv_import is None:
                raise SchedulerClosedError(
                    f"fleet: no pushed handoff payload {token!r}")
        elif ref is not None:
            try:
                kv_import = _fetch_handoff(tuple(ref["addr"]),
                                           ref["rid"],
                                           acct=self.wire_acct("bulk"))
            except (ConnectionError, OSError, TimeoutError) as e:
                # source worker gone or payload expired: refuse this
                # candidate crisply so _migrate tries the next one
                raise SchedulerClosedError(
                    f"fleet: handoff fetch failed: {e}") from e
        sr = self.replica.submit(prompt_ids, kv_import=kv_import,
                                 **params)
        rid = str(sr.rid)
        with self._req_lock:
            self._requests[rid] = sr
        return {"rid": sr.rid, "trace_id": sr.trace_id,
                "priority": sr.priority, "slo": sr.slo,
                "output": [int(t) for t in sr.output]}

    def handle_cancel(self, rid):
        with self._req_lock:
            sr = self._requests.get(str(rid))
        return sr.cancel() if sr is not None else False

    # -- fleet observability -------------------------------------------
    def obs_snapshot(self, window=None):
        """One rpc: everything the router needs to merge this worker
        into a fleet trace, flight dump, or capture bundle. Spans ride
        the flight snapshot (kind == "span" events)."""
        sched = self.replica.scheduler
        if hasattr(sched, "pulse"):
            pulse = sched.pulse(window=window)
        else:
            pulse = {"enabled": False}
        return {
            "name": self.name,
            "replica_id": self.replica.replica_id,
            "host": self.host,
            "role": self.replica.role,
            "t_wall": time.time(),
            "flight": _flight.snapshot(reason="fleet.obs"),
            "pulse": pulse,
            "requests": self.replica.recent_requests(64),
        }

    def obs_triggers(self):
        """Light poll target for the plane's obs loop: cumulative
        pulse-trigger totals plus the trace ids in flight. The rpc
        round trips that carry this also feed the router's clock-skew
        estimator — polling IS the sampling cadence."""
        plane = getattr(self.replica.scheduler, "_pulse", None)
        if plane is None:
            return {"triggers": {}, "bundles": [], "trace_ids": []}
        plane.maybe_sample()
        return plane.trigger_state()

    # -- lifecycle -----------------------------------------------------
    def serve_forever(self):
        """Block until a shutdown rpc (or local close) stops the
        worker — the `python -m paddle_tpu.serving.fleet` main loop."""
        self._stop.wait()
        # grace for the in-flight shutdown rpc reply to flush
        time.sleep(0.2)
        self.close()

    def shutdown(self, drain=True, timeout=None):
        ok = self.replica.shutdown(drain=drain, timeout=timeout)
        self._stop.set()
        return ok

    def close(self):
        self._stop.set()
        self._hb_stop.set()
        if self.pages is not None:
            self.pages.stop()
        try:
            self.agent.stop()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        try:
            self._bulk_srv.close()
        except OSError:
            pass
        if _WORKERS.get(self.name) is self:
            _WORKERS.pop(self.name, None)

    def __repr__(self):
        return (f"FleetWorker({self.name!r}, "
                f"replica={self.replica.replica_id!r}, "
                f"host={self.host!r})")


# ---------------------------------------------------------------------------
# router side


class _ReqView:
    """Duck-types the engine-level `Request` fields the HTTP frontend
    reads off a handle (`prompt/output/logprobs/cached_tokens`)."""

    __slots__ = ("rid", "prompt", "output", "logprobs", "cached_tokens")

    def __init__(self, rid, prompt, output):
        self.rid = rid
        self.prompt = list(prompt)
        self.output = list(output)
        self.logprobs = None
        self.cached_tokens = 0


_ERROR_TYPES = {
    "BackpressureError": BackpressureError,
    "SchedulerClosedError": SchedulerClosedError,
    "CrashLoopError": CrashLoopError,
    "DeadlineExceededError": DeadlineExceededError,
    "PoisonedRequestError": PoisonedRequestError,
    "ReplicaKilledError": ReplicaKilledError,
    "SchedulerError": SchedulerError,
    "TimeoutError": TimeoutError,
}


def _rebuild_error(err):
    if err is None:
        return None
    cls = _ERROR_TYPES.get(err.get("type"))
    msg = err.get("msg", "")
    if cls is not None:
        return cls(msg)
    return RuntimeError(f"{err.get('type', 'RemoteError')}: {msg}")


class RemoteRequest:
    """Router-side handle over one request running on a fleet worker.
    Duck-types `ServingRequest`: same terminal states, same
    `stream()/result()/cancel()` semantics, its own `_streamed` flag
    (the point of no replay is when THIS consumer saw a chunk — the
    worker forwarding frames to us does not count). A background
    reader drains the worker's bulk-channel token frames into a local
    queue; transport loss before terminal flips the request to
    "failed" exactly like an engine crash, which is what arms the
    router's failover."""

    def __init__(self, replica, prompt_ids, spec):
        self._replica = replica
        self.rid = spec["rid"]
        self.trace_id = spec.get("trace_id")
        self.priority = spec.get("priority", "normal")
        self.slo = spec.get("slo")
        self.req = _ReqView(self.rid, prompt_ids,
                            spec.get("output") or [])
        self.state = "queued"
        self.error = None
        self.t_first_token = None
        self.timeline = None
        self.slo_attained = None
        self.violated_phase = None
        self.handoff = None
        self._streamed = False
        self.chunks = queue.Queue()
        self._done = threading.Event()
        self._term_lock = threading.Lock()
        self._sock = None
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"pt-fleet-req-{self.rid}")
        self._reader.start()

    @property
    def output(self):
        return list(self.req.output)

    # -- reader ---------------------------------------------------------
    def _read_loop(self):
        acct = self._replica.wire_acct("stream")
        t0 = time.perf_counter()
        try:
            s = socket.create_connection(
                self._replica.bulk_addr,
                timeout=env_float("PT_FLEET_CALL_TIMEOUT_S"))
            # streaming can idle arbitrarily long behind a deep queue;
            # liveness belongs to the heartbeat monitor, which closes
            # this socket when the worker is declared dead
            s.settimeout(None)
            self._sock = s
            _wire.send_json(s, {"op": "stream", "rid": str(self.rid)},
                            acct=acct)
            while True:
                fr = _wire.recv_json(s, acct=acct)
                t = fr.get("t")
                if t == "chunk":
                    toks = [int(x) for x in fr.get("toks") or []]
                    if self.t_first_token is None:
                        self.t_first_token = time.monotonic()
                    self.req.output.extend(toks)
                    self.chunks.put(toks)
                elif t == "end":
                    self._finish(fr)
                    # router half of the stream transfer (the worker
                    # records its half under the same span name)
                    _tc.record_span_event(
                        "wire.stream", time.perf_counter() - t0,
                        trace_id=self.trace_id,
                        args={"rid": str(self.rid),
                              "bytes": acct.rx_bytes,
                              "frames": acct.frames,
                              "worker": self._replica._worker})
                    return
                else:
                    raise _wire.WireError(
                        f"fleet: unexpected stream frame {t!r}")
        except Exception as e:  # noqa: BLE001 — any reader death fails the req
            self._transport_dead(e)
        finally:
            sock, self._sock = self._sock, None
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

    def _finish(self, fr):
        with self._term_lock:
            if self._done.is_set():
                return
            self.req.output = [int(t) for t in fr.get("output") or []]
            self.req.logprobs = fr.get("logprobs")
            self.req.cached_tokens = int(fr.get("cached_tokens") or 0)
            tl = fr.get("timeline")
            self.timeline = Timeline.from_dict(tl) if tl else None
            self.slo = fr.get("slo", self.slo)
            self.slo_attained = fr.get("slo_attained")
            self.violated_phase = fr.get("violated_phase")
            h = fr.get("handoff")
            if h is not None:
                self.handoff = RemoteHandoffRef(
                    self._replica.bulk_addr, str(self.rid),
                    nbytes=h.get("nbytes", 0), pages=h.get("pages", 0))
            err = fr.get("error")
            if err is not None:
                # worker-side failure context survives the frame: the
                # NEXT sever on this replica names it (a crash usually
                # errors one request before it kills the transport)
                self._replica.last_error = (
                    f"{err.get('type', 'Error')}: {err.get('msg', '')}")
            self.error = _rebuild_error(err)
            self.state = fr.get("state", "failed")
            self._done.set()
            self.chunks.put(None)
        self._replica._forget(self.rid)

    def _transport_dead(self, reason):
        """The wire to the worker died before a terminal frame: fail
        the request like an engine crash, carrying the trace id and
        the worker's last known error so the router-side exception
        names WHAT died over there, not just that the socket closed.
        Never-streamed handles then ride the router's existing
        failover (token-identical replay); mid-stream ones surface
        the error."""
        last = self._replica.last_error
        with self._term_lock:
            if self._done.is_set():
                return
            msg = (f"fleet: worker {self._replica._worker!r} lost "
                   f"mid-request: {reason} [trace {self.trace_id}]")
            if last:
                msg += f"; last worker error: {last}"
            err = SchedulerError(msg)
            err.trace_id = self.trace_id
            err.worker_error = last
            self.error = err
            self.state = "failed"
            self._done.set()
            self.chunks.put(None)
        self._replica._forget(self.rid)
        _flight.record("fleet.sever", rid=str(self.rid),
                       worker=self._replica._worker,
                       trace_id=self.trace_id, reason=str(reason),
                       worker_error=last, streamed=self._streamed)

    def _sever(self, reason):
        """Heartbeat monitor path: close the stream socket so the
        blocked reader fails NOW instead of waiting on a dead peer."""
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._transport_dead(reason)

    # -- consumption ----------------------------------------------------
    def stream(self, timeout=None):
        while True:
            chunk = self.chunks.get(timeout=timeout)
            if chunk is None:
                if self.error is not None:
                    raise self.error
                return
            self._streamed = True
            yield chunk

    def result(self, timeout=None):
        if not self._done.wait(timeout=timeout):
            raise TimeoutError(f"request {self.rid}: not done")
        if self.error is not None:
            raise self.error
        return self.output

    def cancel(self):
        if self._done.is_set():
            return False
        try:
            return bool(self._replica._call(_rpc_cancel,
                                            (str(self.rid),)))
        except (ConnectionError, OSError, TimeoutError):
            return False


class _RemoteScheduler:
    """The `replica.scheduler` surface the router's aggregation paths
    consume (/metrics, /debug/requests, /debug/pulse, ledger stats) —
    each method one idempotent rpc with a degraded fallback, so one
    dead worker never breaks a pool-wide scrape."""

    def __init__(self, rep):
        self._rep = rep

    def render_prometheus(self):
        try:
            return self._rep._call(_rpc_render_prometheus,
                                   retries=self._rep._retries)
        except (ConnectionError, OSError, TimeoutError):
            return ""

    def metrics_snapshot(self):
        try:
            return self._rep._call(_rpc_metrics_snapshot,
                                   retries=self._rep._retries)
        except (ConnectionError, OSError, TimeoutError):
            return {}

    def recent_requests(self, n=50):
        try:
            return self._rep._call(_rpc_recent_requests, (int(n),),
                                   retries=self._rep._retries)
        except (ConnectionError, OSError, TimeoutError):
            return []

    def pulse(self, window=None, signals=None):
        try:
            return self._rep._call(_rpc_pulse, (window, signals),
                                   retries=self._rep._retries)
        except (ConnectionError, OSError, TimeoutError):
            return {"enabled": False}

    def stats(self):
        return self._rep.stats()

    # registry-surface alias: this object doubles as the proxy's
    # `registry`, and registry consumers call snapshot()
    snapshot = metrics_snapshot


_DEAD_LOAD = 1 << 30


class RemoteReplica:
    """`Replica` duck-type over a fleet worker: every control call is
    an rpc to the worker's agent; submits return `RemoteRequest`
    handles fed by the worker's bulk channel. Transport failures
    degrade, never crash the router: submit translates to
    `SchedulerClosedError` (the dispatch plan spills to the next
    candidate), stats/load return worst-case values, and a dead
    marking (heartbeat loss or connection refusal) fails in-flight
    requests through the same path an engine crash would take."""

    def __init__(self, agent, worker_name, meta):
        self._agent = agent
        self._worker = str(worker_name)
        self.replica_id = str(meta["replica_id"])
        self.role = meta.get("role", "both")
        self.page_size = int(meta["page_size"])
        self.max_queue = int(meta.get("max_queue", 64))
        self.host = meta.get("host")
        self.bulk_addr = (meta["bulk_ip"], int(meta["bulk_port"]))
        self._dead = threading.Event()
        self._dead_reason = None
        # last worker-side error string seen on this replica's wire
        # (terminal stream frames); attached to sever exceptions.
        # Plain attribute: single writer per frame, torn reads benign
        self.last_error = None
        self._live = {}
        self._live_lock = threading.Lock()
        # wire accounting: counters live on the fleet plane's registry
        # (installed by FleetPlane); bare local tallies until then
        self._wire_registry = None
        self._wire_counters = {}
        self._retries = env_int("PT_FLEET_RETRIES")
        self._timeout = env_float("PT_FLEET_CALL_TIMEOUT_S")
        self._last_stats = {
            "replica_id": self.replica_id, "role": self.role,
            "ready": False, "closed": False, "paused": False,
            "queued": 0, "inflight": 0, "active": 0,
            "engine_waiting": 0, "device_steps": 0, "preemptions": 0,
            "requests": {"submitted": 0, "started": 0, "completed": 0,
                         "failed": 0, "cancelled": 0, "expired": 0,
                         "requeued": 0, "handoff": 0},
        }
        self.scheduler = _RemoteScheduler(self)
        self.registry = self.scheduler

    def wire_acct(self, chan):
        """Router-side mirror of `FleetWorker.wire_acct`: a fresh
        account bound to pt_wire_* counters on the plane registry, or
        tallies-only when no plane installed one (in-process tests)."""
        c = self._wire_counters.get(chan)
        if c is None:
            r = self._wire_registry
            if r is None:
                return _wire.WireAccount()
            c = (r.counter("pt_wire_tx_bytes",
                           "Bytes framed onto fleet sockets (header + "
                           "payload).", labels={"chan": chan}),
                 r.counter("pt_wire_rx_bytes",
                           "Bytes received off fleet sockets (header + "
                           "payload).", labels={"chan": chan}),
                 r.counter("pt_wire_frames",
                           "Frames moved over fleet sockets, both "
                           "directions.", labels={"chan": chan}))
            self._wire_counters[chan] = c
        return _wire.WireAccount(tx=c[0], rx=c[1], frames=c[2])

    # -- rpc plumbing ---------------------------------------------------
    def _call(self, fn, args=(), timeout=None, retries=0):
        if self._dead.is_set():
            raise ConnectionError(
                f"fleet: worker {self._worker!r} is dead "
                f"({self._dead_reason})")
        timeout = self._timeout if timeout is None else timeout
        last = None
        for attempt in range(int(retries) + 1):
            try:
                fut = self._agent.invoke(self._worker, fn,
                                         (self._worker,) + tuple(args),
                                         {}, timeout)
                return fut.wait(timeout + 5.0)
            except (ConnectionRefusedError,) as e:
                # nobody listening on a known port: the process is gone
                self._mark_dead(f"connection refused: {e}")
                raise
            except (ConnectionError, OSError, TimeoutError) as e:
                last = e
                if attempt < retries:
                    time.sleep(min(0.05 * (2 ** attempt), 0.5))
        raise last

    def _forget(self, rid):
        with self._live_lock:
            self._live.pop(str(rid), None)

    def _mark_dead(self, reason):
        """Liveness lost (heartbeat stall / connection refused): fail
        every in-flight request so the router's breaker and failover
        react exactly as they would to a local engine crash."""
        if self._dead.is_set():
            return
        self._dead_reason = reason
        self._dead.set()
        with self._live_lock:
            live = list(self._live.values())
            self._live.clear()
        for rr in live:
            rr._sever(reason)
        _flight.record("fleet.worker_dead", worker=self._worker,
                       replica=self.replica_id, reason=str(reason),
                       inflight=len(live))

    @property
    def alive(self):
        return not self._dead.is_set()

    # -- Replica duck-type ---------------------------------------------
    def prefill_eligible(self):
        return self.role in ("prefill", "both")

    def decode_eligible(self):
        return self.role in ("decode", "both")

    def stats(self):
        try:
            st = self._call(_rpc_stats, retries=self._retries)
        except (ConnectionError, OSError, TimeoutError):
            st = dict(self._last_stats)
            st.update(ready=False, closed=self._dead.is_set(),
                      queued=0, inflight=0, active=0)
            st["host"] = self.host
            return st
        st["host"] = self.host
        self._last_stats = dict(st)
        return st

    def load(self):
        try:
            return int(self._call(_rpc_load, retries=self._retries))
        except (ConnectionError, OSError, TimeoutError):
            return _DEAD_LOAD       # sorts last in every spill order

    def ready(self):
        try:
            return bool(self._call(_rpc_ready, retries=self._retries))
        except (ConnectionError, OSError, TimeoutError):
            return False

    def recent_requests(self, n=50):
        return self.scheduler.recent_requests(n)

    def submit(self, prompt_ids, **params):
        if self._dead.is_set():
            raise SchedulerClosedError(
                f"fleet: worker {self._worker!r} is dead "
                f"({self._dead_reason})")
        prompt_ids = [int(t) for t in prompt_ids]
        kv_import = params.pop("kv_import", None)
        if kv_import is not None:
            if isinstance(kv_import, RemoteHandoffRef):
                # reference only: the worker fetches the pages straight
                # from the source worker's bulk endpoint (host-to-host)
                params["kv_import_ref"] = {
                    "addr": list(kv_import.addr), "rid": kv_import.rid}
            else:
                # the payload lives in THIS process (local-replica
                # source): push it over the bulk channel, then submit
                # by token
                try:
                    _push_handoff(self.bulk_addr, kv_import,
                                  acct=self.wire_acct("bulk"))
                except (ConnectionError, OSError, TimeoutError) as e:
                    raise SchedulerClosedError(
                        f"fleet: handoff push to {self._worker!r} "
                        f"failed: {e}") from e
                params["kv_import_token"] = str(kv_import.rid)
        try:
            # a router-side span per dispatch: the rpc ships its trace
            # meta, so the worker's spans nest under this one in the
            # stitched fleet trace
            with _tc.span("fleet.submit",
                          args={"worker": self._worker,
                                "replica": self.replica_id}):
                spec = self._call(_rpc_submit, (prompt_ids, params))
        except (ConnectionError, OSError, TimeoutError) as e:
            raise SchedulerClosedError(
                f"fleet: worker {self._worker!r} unreachable: "
                f"{e}") from e
        rr = RemoteRequest(self, prompt_ids, spec)
        with self._live_lock:
            self._live[str(rr.rid)] = rr
        return rr

    # -- operational controls ------------------------------------------
    def pause(self):
        try:
            self._call(_rpc_pause, retries=self._retries)
        except (ConnectionError, OSError, TimeoutError):
            pass

    def resume(self):
        try:
            self._call(_rpc_resume, retries=self._retries)
        except (ConnectionError, OSError, TimeoutError):
            pass

    def drain(self, timeout=None):
        try:
            rpc_to = (timeout or 60.0) + 10.0
            return bool(self._call(_rpc_drain, (timeout,),
                                   timeout=rpc_to))
        except (ConnectionError, OSError, TimeoutError):
            return False

    def shutdown(self, drain=True, timeout=None):
        try:
            rpc_to = (timeout or 60.0) + 10.0
            return bool(self._call(_rpc_shutdown, (drain, timeout),
                                   timeout=rpc_to))
        except (ConnectionError, OSError, TimeoutError):
            # a dead worker is as shut down as it will ever be
            return self._dead.is_set()

    def kill(self):
        self._call(_rpc_kill)

    def revive(self):
        self._call(_rpc_revive)

    def __repr__(self):
        state = "dead" if self._dead.is_set() else "up"
        return (f"RemoteReplica({self.replica_id!r}, "
                f"worker={self._worker!r}, host={self.host!r}, {state})")


class FleetPlane:
    """Router-side fleet bring-up and liveness. Hosts the rendezvous
    store as rpc rank 0, waits for every expected worker's meta,
    builds the `RemoteReplica` pool (`.replicas` goes straight into
    `Router(...)`), and runs the heartbeat monitor: a worker whose
    store-key beat stalls past PT_FLEET_HB_MISS_S is marked dead —
    in-flight requests fail over, the breaker opens, dispatch skips
    it. Sequence-based liveness: no cross-host clock agreement
    needed."""

    def __init__(self, master_endpoint, workers, *, metrics=None,
                 hb_timeout_s=None, capture_dir=None):
        workers = list(workers)
        host, port = str(master_endpoint).rsplit(":", 1)
        self.master_endpoint = f"{host}:{int(port)}"
        self._store = _rpc._TCPStore(host, int(port), True)
        try:
            self._agent = _rpc.RpcAgent(ROUTER_NAME, 0,
                                        len(workers) + 1, self._store)
        except BaseException:
            self._store.stop()
            raise
        self.registry = metrics if isinstance(metrics, MetricsRegistry) \
            else MetricsRegistry()
        self.workers_gauge = self.registry.gauge(
            "pt_fleet_workers", "Fleet workers registered.")
        self.workers_alive = self.registry.gauge(
            "pt_fleet_workers_alive",
            "Fleet workers currently passing heartbeat liveness.")
        self.hb_misses = self.registry.counter(
            "pt_fleet_heartbeat_misses",
            "Workers declared dead after a stalled heartbeat.")
        self.replicas = []
        for name in workers:
            meta = self._store.get(f"fleet/meta/{name}")
            self.replicas.append(RemoteReplica(self._agent, name, meta))
        self.workers_gauge.set(len(self.replicas))
        self.workers_alive.set(len(self.replicas))
        self._hb_timeout = float(
            hb_timeout_s if hb_timeout_s is not None
            else env_float("PT_FLEET_HB_MISS_S"))
        self._stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="pt-fleet-monitor")
        self._monitor.start()

        # -- fleet observability ---------------------------------------
        # clock-skew estimation rides every rpc reply; the obs loop
        # polls worker trigger totals and fires fleet capture bundles
        self.clock = _fobs.ClockSkewEstimator()
        self._clock_gauges = {}      # worker -> (offset_g, unc_g)
        self._agent.on_clock_sample = self._on_clock_sample
        for rep in self.replicas:
            rep._wire_registry = self.registry
        self.capture_dir = capture_dir if capture_dir is not None \
            else (env_str("PT_FLEET_CAPTURE_DIR") or None)
        self.capture_max = env_int("PT_FLEET_CAPTURE_MAX")
        self.capture_min_s = env_float("PT_FLEET_CAPTURE_MIN_S")
        self.fleet_bundles = []
        self.fleet_captures = self.registry.counter(
            "pt_fleet_capture_bundles",
            "Fleet-wide capture bundles written on a worker pulse "
            "trigger.")
        self._bundle_lock = threading.Lock()
        self._bundle_last_t = 0.0
        self._trig_seen = {}         # worker -> last trigger totals
        self._obs_interval = env_float("PT_FLEET_OBS_POLL_S")
        # separate thread from _monitor_loop on purpose: an rpc stall
        # polling one worker must not delay heartbeat liveness checks
        self._obs_thread = threading.Thread(
            target=self._obs_loop, daemon=True, name="pt-fleet-obs")
        self._obs_thread.start()

    def replica(self, name_or_rid):
        for rep in self.replicas:
            if name_or_rid in (rep._worker, rep.replica_id):
                return rep
        return None

    # -- liveness -------------------------------------------------------
    def _hb_seq(self, name):
        # the plane hosts the master store: read the key directly
        # instead of dialing our own socket once per worker per tick
        st = self._store
        with st._cv:
            return st._data.get(f"fleet/hb/{name}")

    def _monitor_loop(self):
        interval = env_float("PT_FLEET_HB_S")
        seen = {}                    # worker -> (seq, t_last_change)
        while not self._stop.wait(interval):
            now = time.monotonic()
            n_alive = 0
            for rep in self.replicas:
                if rep._dead.is_set():
                    continue
                name = rep._worker
                seq = self._hb_seq(name)
                prev = seen.get(name)
                if prev is None or seq != prev[0]:
                    seen[name] = (seq, now)
                    n_alive += 1
                elif now - prev[1] > self._hb_timeout:
                    self.hb_misses.inc()
                    rep._mark_dead(
                        f"heartbeat stalled > {self._hb_timeout:g}s")
                else:
                    n_alive += 1
            self.workers_alive.set(n_alive)

    # -- fleet observability --------------------------------------------
    def _on_clock_sample(self, peer, t_send, t_remote, t_recv,
                         hold_s=0.0):
        """RpcAgent hook: one NTP-style sample per rpc reply. Feeds
        the EWMA estimator and the per-host offset gauges."""
        off, unc = self.clock.sample(peer, t_send, t_remote, t_recv,
                                     hold_s)
        g = self._clock_gauges.get(peer)
        if g is None:
            rep = self.replica(peer)
            host = (rep.host if rep is not None else None) or peer
            g = (self.registry.gauge(
                     "pt_fleet_clock_offset_seconds",
                     "EWMA-smoothed clock offset of a worker host "
                     "relative to the router (positive = worker clock "
                     "ahead).", labels={"host": host}),
                 self.registry.gauge(
                     "pt_fleet_clock_uncertainty_seconds",
                     "Half-RTT uncertainty bound on the worker "
                     "clock-offset estimate.", labels={"host": host}))
            # benign race: registry dedups by (name, labels)
            self._clock_gauges[peer] = g
        g[0].set(off)
        g[1].set(unc)

    def _obs_loop(self):
        """Poll each alive worker's pulse-trigger totals (one light
        rpc per worker per tick — the same round trips keep the clock
        estimator fed) and pull ONE fleet capture bundle when any
        worker reports a new trigger fire."""
        while not self._stop.wait(self._obs_interval):
            fired = None
            trace_ids = []
            for rep in self.replicas:
                if rep._dead.is_set():
                    continue
                try:
                    st = rep._call(_rpc_obs_triggers,
                                   timeout=self._obs_interval * 2)
                except (ConnectionError, OSError, TimeoutError):
                    continue
                cur = st.get("triggers") or {}
                prev = self._trig_seen.get(rep._worker)
                self._trig_seen[rep._worker] = cur
                if prev is None:
                    continue         # first poll: baseline only
                for trig in sorted(cur):
                    if float(cur[trig]) > float(prev.get(trig, 0)):
                        if fired is None:
                            fired = (trig, rep._worker)
                        break
                trace_ids.extend(st.get("trace_ids") or [])
            if fired is not None:
                try:
                    self._fleet_capture(fired[0], fired[1], trace_ids)
                except Exception as e:  # noqa: BLE001 — capture is best-effort
                    _flight.record("fleet.capture_error",
                                   trigger=fired[0], error=repr(e))

    def _fleet_capture(self, trigger, worker, trace_ids):
        """Rank 0's incident response: pull every worker's flight dump
        + pulse window + request ring into ONE bundle dir with
        per-host subdirs. Rate-limited; returns the path or None."""
        if self.capture_dir is None:
            return None
        now = time.monotonic()
        with self._bundle_lock:
            if len(self.fleet_bundles) >= self.capture_max:
                return None
            if self.fleet_bundles \
                    and now - self._bundle_last_t < self.capture_min_s:
                return None
            self._bundle_last_t = now
            seq = len(self.fleet_bundles)
            # reserve the slot before the (slow, networked) pull so a
            # second trigger in the same window rate-limits against it
            self.fleet_bundles.append(None)
        sections = self.obs_sections()
        stamp = time.strftime("%Y%m%d-%H%M%S")
        name = f"fleet-{stamp}-{seq:03d}-{trigger}-{os.getpid()}"
        meta = {"trigger": trigger, "worker": worker,
                "at": time.time(), "pid": os.getpid(),
                "trace_ids": list(dict.fromkeys(trace_ids)),
                "clock": self.clock.snapshot()}
        path = _fobs.write_fleet_bundle(self.capture_dir, name, meta,
                                        sections)
        with self._bundle_lock:
            self.fleet_bundles[seq] = path
        self.fleet_captures.inc()
        _flight.record("fleet.bundle", trigger=trigger, worker=worker,
                       path=path, trace_ids=meta["trace_ids"] or None)
        return path

    def obs_sections(self, window=None):
        """One section per fleet process: the router's own flight ring
        plus every alive worker's obs snapshot pulled over rpc (all
        network round trips happen OUTSIDE any lock). Each worker
        section carries the clock offset used to rebase it."""
        sections = [{
            "label": ROUTER_NAME,
            "host": socket.gethostname(),
            "replica_id": None,
            "offset_s": 0.0, "uncertainty_s": 0.0,
            "flight": _flight.snapshot(reason="fleet.obs"),
            "pulse": {"enabled": False},
            "requests": [],
        }]
        for rep in self.replicas:
            if rep._dead.is_set():
                continue
            try:
                snap = rep._call(_rpc_obs_snapshot, (window,))
            except (ConnectionError, OSError, TimeoutError):
                continue             # a dead worker is just absent
            snap["label"] = (f"{snap.get('replica_id')}"
                             f"@{snap.get('host')}")
            snap["offset_s"] = self.clock.offset(rep._worker)
            snap["uncertainty_s"] = self.clock.uncertainty(rep._worker)
            sections.append(snap)
        return sections

    def fleet_trace(self):
        """GET /debug/fleet/trace: one merged chrome-trace document,
        one process row per replica@host (plus the router), remote
        timestamps rebased onto the router clock, cross-process flow
        arrows per trace id."""
        sections = []
        for sec in self.obs_sections():
            spans = [e for e in
                     ((sec.get("flight") or {}).get("events") or [])
                     if e.get("kind") == "span"]
            sections.append({"label": sec["label"],
                             "offset_s": sec.get("offset_s", 0.0),
                             "spans": spans})
        return _fobs.stitch_fleet_trace(sections)

    def fleet_flightrecorder(self):
        """GET /debug/fleet/flightrecorder: every process's flight
        ring in one document — per-host sections plus one merged
        stream on the skew-corrected fleet clock."""
        return _fobs.merge_flight_sections(self.obs_sections())

    # -- lifecycle ------------------------------------------------------
    def shutdown_workers(self, drain=True, timeout=None):
        """Stop every worker process's replica + serve loop (the
        Router's own shutdown() does this too when it owns the
        replicas; this is the direct path for plane-only teardown)."""
        ok = True
        for rep in self.replicas:
            ok = rep.shutdown(drain=drain, timeout=timeout) and ok
        return ok

    def close(self):
        """Tear down the control plane (monitor, agent, store). Call
        after the Router/workers are shut down."""
        self._stop.set()
        try:
            self._agent.stop()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        self._store.stop()


def connect_fleet(master_endpoint, workers, **kw):
    """Bring up the router side of a fleet: host the rendezvous at
    `master_endpoint`, wait for the named `workers`, return a
    `FleetPlane` whose `.replicas` drop straight into `Router(...)`.
    See docs/serving.md § Fleet plane for the full topology."""
    return FleetPlane(master_endpoint, workers, **kw)


# ---------------------------------------------------------------------------
# worker process entrypoint


def spawn_worker(spec, *, python=None, env=None, stdout=None,
                 stderr=None):
    """Launch one fleet worker as a subprocess:
    ``python -m paddle_tpu.serving.fleet --spec '<json>'``. The spec
    is a plain-JSON dict:

      {"name": "w0", "master": "127.0.0.1:29500", "rank": 1,
       "world_size": 3, "role": "prefill", "seed": 0,
       "model": {<LlamaConfig fields>}, "dtype": "float32",
       "engine": {<ServingEngine kwargs>}, "replica": {<Replica kw>},
       "host": "optional-host-label"}

    The child builds its engine deterministically from
    (model, seed, dtype) — the cross-process token-identity
    guarantee: same spec, same params, same trajectories."""
    import subprocess
    cmd = [python or sys.executable, "-m", "paddle_tpu.serving.fleet",
           "--spec", json.dumps(spec)]
    e = dict(os.environ)
    if env:
        e.update(env)
    return subprocess.Popen(cmd, env=e, stdout=stdout, stderr=stderr)


def run_worker(spec):
    """Build engine + replica + FleetWorker from a spawn spec and
    serve until shut down. Model/engine imports live HERE — the
    serving package stays import-cycle-free."""
    import jax.numpy as jnp

    from ..models import llama_spmd as M
    from ..models.llama import LlamaConfig
    from ..models.llama_serving import ServingEngine
    from .replica import Replica

    cfg = LlamaConfig(**spec["model"])
    dtype = jnp.dtype(spec.get("dtype", "float32"))
    params = M.init_params(cfg, seed=int(spec.get("seed", 0)),
                           dtype=dtype)
    engine = ServingEngine(params, cfg, dtype=dtype,
                           **(spec.get("engine") or {}))
    replica = Replica(spec.get("replica_id", spec["name"]), engine,
                      role=spec.get("role", "both"),
                      **(spec.get("replica") or {}))
    worker = FleetWorker(spec["name"], replica,
                         master_endpoint=spec["master"],
                         rank=int(spec["rank"]),
                         world_size=int(spec["world_size"]),
                         host=spec.get("host"))
    worker.serve_forever()
    # leave a breadcrumb: crashes dump via the install()ed handlers,
    # clean exits dump here — either way the worker's flight ring
    # survives the process and its path is on stderr
    try:
        path = _flight.dump(reason="fleet.worker_exit")
        print(f"fleet: worker {spec['name']} flight dump: {path}",
              file=sys.stderr, flush=True)
    except Exception:  # noqa: BLE001 — exit breadcrumb is best-effort
        pass
    return 0


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.fleet",
        description="Run one fleet worker process.")
    ap.add_argument("--spec", required=True,
                    help="worker spec as a JSON string, or @path to a "
                         "JSON file")
    args = ap.parse_args(argv)
    raw = args.spec
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    return run_worker(json.loads(raw))


if __name__ == "__main__":
    # re-enter through the CANONICAL module: running under `-m` loads
    # this file as __main__, but inbound rpc frames reference
    # `paddle_tpu.serving.fleet._rpc_*` — the worker must register in
    # THAT module's _WORKERS, not a __main__ shadow copy
    from paddle_tpu.serving import fleet as _canonical
    sys.exit(_canonical.main())
