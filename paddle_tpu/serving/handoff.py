"""KV-page handoff payloads for disaggregated prefill/decode serving.

A prefill-role replica finishes a request's prompt (and seeds its first
token), then exports the request's KV pages as a `KVHandoff` — plain
numpy bytes + metadata, produced by the kvtier copy thread's explicit
device->host fence (`HostTier.export_pages`). The router hands the
payload to a decode-role replica, whose scheduler re-submits the
request with ``kv_import=payload``; the engine scatters the pages back
through the preemption swap-in path (`_scatter_host_kv`) and
generation continues token-identically — the device sampler's PRNG is
a pure function of (seed, position), so the trajectory survives the
migration bit-exactly.

The payload is deliberately transport-agnostic: arrays and ints only,
no engine or jax object references, so the in-process handoff the
Router performs today can be backed by the rpc/collective layer for
multi-host pools without changing either engine's import/export code.

Page encoding follows the exporting tier's setting: ``quantized=True``
payloads carry int8 pages + per-token fp32 scales (the kvtier wire
format — lossless over an int8 pool, ~4x smaller over an fp pool);
``quantized=False`` carries the pool dtype verbatim. The importer
dequantizes (or re-quantizes) host-side to match its own pool.

Pure stdlib + numpy — importable from tests, benches and ops tooling
without pulling in jax or model code.
"""
from __future__ import annotations

__all__ = ["KVHandoff"]


class KVHandoff:
    """One request's exported KV state, mid-generation.

    k/v: (L, KVH, pages, page_size, D) numpy; ks/vs: matching
    (..., 1) fp32 per-token scales or None. `length` is the cache
    length the pages are valid to (== len(prompt) + len(output) - 1:
    everything decided except the pending `next_token`, which rides as
    metadata exactly like a preemption resume)."""

    __slots__ = ("rid", "trace_id", "prompt", "output", "next_token",
                 "length", "pages", "k", "v", "ks", "vs", "quantized",
                 "logprobs", "cached_tokens", "timeline")

    def __init__(self, rid, prompt, output, next_token, length, pages,
                 k, v, ks=None, vs=None, quantized=False, trace_id=None,
                 logprobs=None, cached_tokens=0, timeline=None):
        self.rid = rid
        self.trace_id = trace_id
        self.prompt = list(prompt)
        self.output = list(output)
        self.next_token = int(next_token)
        self.length = int(length)
        self.pages = int(pages)
        self.k = k
        self.v = v
        self.ks = ks
        self.vs = vs
        self.quantized = bool(quantized)
        self.logprobs = None if logprobs is None else list(logprobs)
        self.cached_tokens = int(cached_tokens)
        # Timeline.to_dict() of the exporting side (or None): plain
        # lists/floats so the payload stays transport-agnostic; the
        # importing scheduler stitches it into the resumed request.
        self.timeline = timeline

    @property
    def nbytes(self):
        """Wire size of the KV payload (metadata excluded) — what a
        multi-host backing would actually ship."""
        return sum(a.nbytes for a in (self.k, self.v, self.ks, self.vs)
                   if a is not None)

    def __repr__(self):
        return (f"KVHandoff(rid={self.rid!r}, length={self.length}, "
                f"pages={self.pages}, quantized={self.quantized}, "
                f"nbytes={self.nbytes})")
