"""Prefix KV cache: ref-counted page sharing over the paged KV pool.

The serving engine (`models/llama_serving.ServingEngine`) stores KV in
fixed-size pages addressed through per-slot `page_table` rows. Prompts
in production traffic share long prefixes — system prompts, few-shot
headers, multi-turn history — and every token of a shared prefix
produces *identical* KV at identical positions. This module turns that
into cache hits (reference parity: SGLang RadixAttention / vLLM
automatic prefix caching; the Gemma-on-TPU serving study's "KV reuse
wins TTFT" observation):

  * `PagePool` — the single allocator every page-lifetime path goes
    through (admission, finish, cancellation sweep, preemption
    offload/restore). Pages are ref-counted so N concurrent requests
    can map the same physical page into their page-table rows; a page
    is reclaimable only at refcount 0.
  * `PrefixCache` — indexes FULL pages by a chained block hash of
    their token ids (radix-style: block i's key folds block i-1's key,
    so a lookup is a longest-prefix walk). Refcount-0 pages that are
    still indexed park in an LRU instead of the free list; allocation
    reclaims them (evicting their index entries) before the pool is
    declared empty.

Everything here is host-side numpy/stdlib by design — the bookkeeping
runs between device steps, never inside traced code, and must not add
host<->device traffic (tpulint-clean, zero suppressions).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..observability import flight_recorder as _flight

__all__ = ["PagePool", "PrefixCache", "block_hash"]

# chain seed: block 0's parent "hash"
_SEED = 0x9E3779B9


def block_hash(parent, block):
    """Chained hash of one full page of token ids under its parent
    block's hash. Module-level (not a method) so tests can patch in a
    colliding function; entries store (parent, block) raw for
    verification, so a collision degrades to a cache miss, never to
    wrong KV."""
    return hash((parent, block))


class PrefixCache:
    """Radix-style index of full KV pages by chained block hash.

    An entry maps `hash(parent_key, page_tokens)` to the physical page
    holding that block's KV. Entries exist only while the page does:
    a page is indexed while live (refcount > 0) or parked in the LRU
    (refcount 0, reclaimable); eviction removes the entry before the
    page is re-issued. The trash page never reaches this class — the
    pool only manages allocatable ids.
    """

    def __init__(self, page_size):
        self.page_size = int(page_size)
        # chained hash -> (page, parent, block, depth); depth is the
        # 1-based block index, carried so the host tier's drop policy
        # knows how deep a spilled page sits in its chain
        self.entries = {}
        self._page_key = {}      # indexed page -> chained hash
        self._lru = OrderedDict()  # rc==0 indexed pages; oldest evicted first
        # rollups (the engine's metrics hook mirrors these to /metrics)
        self.lookups = 0
        self.hits = 0
        self.tokens_reused = 0
        self.evictions = 0
        self.on_evict = None     # callable(page), set by the engine
        # spill hook (serving/kvtier.py): called with the evicted
        # entry's identity BEFORE the page id is re-issued, so the
        # engine can demote its KV to the host tier instead of
        # discarding it. None = evictions discard (seed behavior).
        self.on_spill = None     # callable(page, parent, block, depth)

    # -- radix walk ---------------------------------------------------
    def _blocks(self, tokens, limit):
        ps = self.page_size
        for b in range(max(int(limit), 0) // ps):
            yield tuple(int(t) for t in tokens[b * ps:(b + 1) * ps])

    def match(self, tokens):
        """Longest-prefix lookup: walk full blocks of `tokens` while
        every block's entry exists AND verifies (raw token compare —
        a hash collision falls back to no-reuse). Capped one token
        short of len(tokens): the engine must always prefill at least
        one suffix token to produce next-token logits.
        Returns (pages, n_cached_tokens)."""
        pages = []
        parent = _SEED
        for block in self._blocks(tokens, len(tokens) - 1):
            h = block_hash(parent, block)
            e = self.entries.get(h)
            if e is None or e[1] != parent or e[2] != block:
                break
            pages.append(e[0])
            parent = h
        return pages, len(pages) * self.page_size

    def insert(self, tokens, pages, limit):
        """Index `pages[i]` under block i's chained hash, for every
        full block below `limit` tokens. Existing verified entries are
        kept (first writer wins — duplicate pages from a concurrent
        cold admission stay private and free normally); a colliding
        foreign entry stops the chain. Returns #entries added."""
        parent = _SEED
        added = 0
        for i, block in enumerate(self._blocks(tokens, limit)):
            h = block_hash(parent, block)
            e = self.entries.get(h)
            if e is None:
                pg = int(pages[i])
                # one key per page: never re-index a page that is
                # already serving a different chain position
                if pg not in self._page_key:
                    self.entries[h] = (pg, parent, block, i + 1)
                    self._page_key[pg] = h
                    added += 1
            elif e[1] != parent or e[2] != block:
                break            # collision: leave the foreign entry alone
            parent = h
        return added

    # -- refcount-0 parking / revival / eviction ----------------------
    def park(self, page):
        """Pool callback at refcount 0: keep an indexed page
        reclaimable-but-cached (MRU end of the LRU) instead of freeing
        it. Returns False for unindexed pages (caller frees them)."""
        key = self._page_key.get(page)
        if key is None:
            return False
        self._lru[page] = key
        return True

    def revive(self, page):
        """Pool callback when a cached page is re-shared (incref from
        0): it leaves the LRU — no longer reclaimable."""
        self._lru.pop(page, None)

    def evict_lru(self):
        """Reclaim the least-recently-parked page: its index entry is
        removed (descendant entries become unreachable and age out)
        and the page id is returned to the allocator. With a spill
        hook wired, the entry's KV is demoted to the host tier first —
        the hook runs BEFORE the page can be re-issued, while its
        contents are still the indexed block's."""
        page, key = self._lru.popitem(last=False)
        e = self.entries.pop(key, None)
        del self._page_key[page]
        self.evictions += 1
        _flight.record("kvcache.evict", page=int(page),
                       cached_pages=len(self._lru))
        spill = self.on_spill
        if spill is not None and e is not None:
            spill(int(page), e[1], e[2], e[3])
        cb = self.on_evict
        if cb is not None:
            cb(int(page))
        return page

    # -- introspection ------------------------------------------------
    def is_indexed(self, page):
        return page in self._page_key

    @property
    def cached_pages(self):
        """Refcount-0 pages currently parked (reclaimable)."""
        return len(self._lru)

    @property
    def hit_rate(self):
        return self.hits / self.lookups if self.lookups else 0.0

    def stats(self):
        return {"lookups": self.lookups, "hits": self.hits,
                "hit_rate": self.hit_rate,
                "tokens_reused": self.tokens_reused,
                "evictions": self.evictions,
                "entries": len(self.entries),
                "cached_pages": len(self._lru)}


class PagePool:
    """Ref-counted allocator over the engine's allocatable page ids
    (0..num_pages-1 — the engine's trash page is NOT in the pool and
    can never be indexed, shared, or evicted).

    Page lifetime: alloc() -> refcount 1 (exclusive owner);
    incref() -> shared by another page-table row; decref() at release —
    at 0 the page parks in the prefix cache's LRU if still indexed,
    else returns to the free list. alloc() reclaims LRU pages before
    declaring the pool empty, so a full cache never blocks admission.
    """

    def __init__(self, num_pages, cache=None):
        self.num_pages = int(num_pages)
        # pop() from the tail hands out page 0 first — same
        # deterministic order as the engine's original free list
        self.free = list(range(self.num_pages - 1, -1, -1))
        self.refcount = np.zeros(self.num_pages, np.int32)
        self.cache = cache

    def available(self):
        """Allocatable right now: free pages + reclaimable (rc==0)
        cached pages. Admission accounting budgets against this."""
        n = len(self.free)
        if self.cache is not None:
            n += self.cache.cached_pages
        return n

    def can_alloc(self, n):
        return self.available() >= n

    def alloc(self, n):
        """Hand out n pages at refcount 1, evicting LRU-cached pages
        as needed. Raises before mutating anything when the pool
        genuinely cannot satisfy the request."""
        if self.available() < n:
            raise RuntimeError("serving: out of KV pages")
        out = []
        for _ in range(n):
            if not self.free:
                self.free.append(self.cache.evict_lru())
            pg = self.free.pop()
            self.refcount[pg] = 1
            out.append(pg)
        return out

    def incref(self, pages):
        """Share pages into another holder's page table. A cached
        (rc==0) page is revived out of the LRU."""
        for pg in pages:
            if self.refcount[pg] == 0 and self.cache is not None:
                self.cache.revive(pg)
            self.refcount[pg] += 1

    def decref(self, pages):
        """Drop one holder. Refcounts can never go negative — an
        underflow means a double-free in the engine and is a hard
        error, not a silent corruption."""
        for pg in pages:
            rc = int(self.refcount[pg]) - 1
            if rc < 0:
                raise RuntimeError(
                    f"kvcache: refcount underflow on page {int(pg)} "
                    "(double release)")
            self.refcount[pg] = rc
            if rc == 0:
                if self.cache is not None and self.cache.park(pg):
                    continue
                self.free.append(pg)

    def counts(self):
        """Conservation invariant probe: free + cached + live must
        always equal num_pages."""
        return {"free": len(self.free),
                "cached": self.cache.cached_pages
                if self.cache is not None else 0,
                "live": int((self.refcount > 0).sum())}

    def conserved(self, drained=False):
        """True when every page is accounted for (free + cached + live
        == num_pages). With `drained=True` additionally no page may
        still be live — after a full drain a lingering refcount is a
        leak (it satisfies conservation but is never reclaimable).

        The invariant is strictly PER POOL: a disaggregated KV handoff
        (serving/handoff.py) copies page bytes out and releases them
        here, then the importing engine allocates from its OWN pool —
        pages never migrate between ledgers, so both sides must stay
        conserved through every export/import/failure path."""
        c = self.counts()
        ok = c["free"] + c["cached"] + c["live"] == self.num_pages
        if drained:
            ok = ok and c["live"] == 0
        return ok
