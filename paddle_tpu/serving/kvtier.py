"""KV-cache tiering: a bounded host-RAM spill tier under the device
prefix cache.

The device-side `PrefixCache` (serving/kvcache.py) parks refcount-0
pages in an LRU and *discards* them when allocation needs the page
back — a multi-turn conversation that returns after a busy burst
re-prefills its whole history from scratch. HBM capacity is the
effective caching ceiling (the Gemma-on-TPU serving study's
per-replica bottleneck); host RAM is 10-100x larger and one PCIe/DMA
copy away. `HostTier` turns the discard into a demotion:

  * **spill**: the prefix cache's eviction hook hands the page's KV
    (sliced off the device pools — jax arrays are functional, so the
    slice stays valid however the pool is rewritten afterwards) to a
    background copy thread. The blocking device→host transfer runs
    THERE, never on the engine's pump thread; the tier indexes the
    landed page under the SAME chained block hash as the device
    cache, so lookup falls through device → host.
  * **restore**: admission's longest-prefix walk continues into the
    tier where the device match ends; hits are scattered back into
    fresh device pages through the engine's preemption
    offload/restore machinery and the request prefills only the
    still-cold suffix — token-identical to a cold run.
  * **quantized storage**: tier pages are stored int8 with per-token
    fp32 scales (the same absmax/127 scheme as the engine's
    `cache_dtype="int8"` pool — `_quantize_host` mirrors
    `ops.paged_attention.quantize_kv` bit-for-bit), stretching host
    capacity ~4x over fp32. An int8 device pool spills its pages
    verbatim (already quantized: the round trip is lossless).
  * **one ledger**: the engine's preemption `preempt_policy="offload"`
    stash lives here too (`stash_put`/`stash_take`), pinned outside
    the drop policy, so ALL host-resident KV is accounted against one
    `tier_bytes` budget instead of an ad-hoc per-request side store.

Budget pressure drops the DEEPEST spilled block first (ties: oldest):
dropping a leaf never orphans descendants, and surviving roots keep
serving partial-prefix hits.

Pure numpy/stdlib at module level — no jax import. The copy worker's
`np.asarray` on a device array IS the explicit fence, and it runs on
the tier's own thread (tpulint TPL001/TPL005 quiet by design: this
module is not in the configured hot-function set and never traces).
"""
from __future__ import annotations

import queue
import threading
from collections import OrderedDict

import numpy as np

from ..observability import flight_recorder as _flight
from . import kvcache as _kvc

__all__ = ["HostTier"]


class _ExportJob:
    """One disaggregated-serving KV export riding the tier's copy
    thread: the caller (engine pump) blocks on `done` while the
    explicit device->host fence runs on the worker — same thread
    discipline as a spill, but the result (and any failure) belongs to
    the WAITING caller, not the copy-error rollup: a failed export
    must degrade that one request to local decode, not silently count
    as a dropped page."""

    __slots__ = ("k", "v", "ks", "vs", "prequantized", "rids",
                 "payload", "error", "done")

    def __init__(self, k, v, ks, vs, prequantized, rids):
        self.k = k
        self.v = v
        self.ks = ks
        self.vs = vs
        self.prequantized = prequantized
        self.rids = rids
        self.payload = None
        self.error = None
        self.done = threading.Event()


def _quantize_host(x):
    """Host-side mirror of `ops.paged_attention.quantize_kv` (absmax/127
    per-token over the head dim, floored scale): np.round is
    half-to-even exactly like jnp.round, so an fp32 page quantized here
    dequantizes to the same values the engine's int8 pool would."""
    xf = np.asarray(x, np.float32)
    scale = np.max(np.abs(xf), axis=-1, keepdims=True) / 127.0
    scale = np.maximum(scale, 1e-8).astype(np.float32)
    q = np.clip(np.round(xf / scale), -127, 127).astype(np.int8)
    return q, scale


def _dequantize_host(q, scale):
    return q.astype(np.float32) * scale


def _nbytes(payload):
    return sum(int(a.nbytes) for a in payload.values() if a is not None)


class HostTier:
    """Bounded host-RAM KV tier: spilled prefix pages + the preemption
    offload stash, one bytes ledger.

    Thread model: the engine's pump thread calls `match`/`note_*`/
    `stash_*`; the tier's own copy worker inserts landed spills. All
    shared state (`_entries`, `_stash`, the ledger and rollups) is
    guarded by `self._lock`; the blocking device→host copy runs
    OUTSIDE the lock on the worker thread.
    """

    def __init__(self, page_size, tier_bytes=0, quantize=True):
        self.page_size = int(page_size)
        self.tier_bytes = int(tier_bytes)
        if self.tier_bytes < 0:
            raise ValueError(f"tier_bytes={tier_bytes}: want >= 0")
        self.quantize = bool(quantize)
        self._lock = threading.Lock()
        # chained hash -> entry dict(parent, block, depth, payload,
        # nbytes); iteration order is recency (move_to_end on touch)
        self._entries = OrderedDict()
        self._stash = {}             # key -> (payload, nbytes, pages)
        self._bytes = 0              # spill entries + stash, together
        # rollups (mirrored to /metrics by EngineMetrics.on_step)
        self.lookups = 0
        self.hits = 0
        self.spills = 0
        self.restores = 0            # pages restored host -> device
        self.drops = 0
        self.copy_errors = 0         # spill copies that failed (page lost)
        self.tokens_reused = 0
        self._q = None
        self._worker = None
        # optional serving.faults.FaultPlan — the engine attaches its
        # own so `tier_spill` drills hit the real copy path
        self.faults = None
        # fleet hooks (serving/fleet.py — multi-host prefix cache).
        # `on_drop(entries)` receives budget-evicted entries AFTER the
        # lock is released (it enqueues spills to the owning peer; a
        # network call under self._lock would stall the pump — TPL004
        # discipline). `fetch_missing(parent, block_idx, tokens)` runs
        # at the end of a short `match`, also outside the lock, and
        # returns extra chain-order payloads fetched from peers. Both
        # None by default: single-host behavior is byte-identical.
        self.on_drop = None
        self.fetch_missing = None

    @property
    def enabled(self):
        """Spill side on? (The stash works regardless: preemption
        offload must not depend on the spill budget being set.)"""
        return self.tier_bytes > 0

    # -- spill (pump thread enqueues; worker thread copies) ------------
    def spill_async(self, parent, block, depth, k, v, ks=None, vs=None,
                    prequantized=False):
        """Queue one evicted page for demotion. `k`/`v` are the page's
        device slices (L, KVH, page, D) — functional jax arrays, so
        they keep their contents while the allocator reuses the page;
        the worker fences them to host (`np.asarray`), quantizes when
        the pool wasn't already int8, and indexes the landed page."""
        if not self.enabled:
            return False
        if self._worker is None:
            self._start_worker()
        self._q.put((parent, block, depth, k, v, ks, vs, prequantized))
        return True

    def _start_worker(self):
        self._q = queue.Queue()
        self._worker = threading.Thread(target=self._copy_loop,
                                        name="pt-kvtier-copy",
                                        daemon=True)
        self._worker.start()

    def _copy_loop(self):
        # one bad page must cost ONE page: an exception anywhere in the
        # fence/quantize/index path drops that page (a future lookup is
        # simply a miss), counts a copy error, leaves an evidence trail,
        # and keeps the daemon alive for every later spill — a dying
        # copy thread would silently turn the tier off
        while True:
            item = self._q.get()
            if isinstance(item, _ExportJob):
                # handoff export: errors propagate to the blocked
                # caller (who degrades to local decode); the page-loss
                # accounting above does not apply
                try:
                    item.payload = self._export(item)
                except BaseException as e:  # noqa: BLE001 — caller's to raise
                    item.error = e
                finally:
                    item.done.set()
                    self._q.task_done()
                continue
            try:
                self._land(*item)
            except Exception as e:  # noqa: BLE001 — a failed spill is a miss
                with self._lock:
                    self.copy_errors += 1
                _flight.record("kvtier.error", error=repr(e))
            finally:
                self._q.task_done()

    def _land(self, parent, block, depth, k, v, ks, vs, prequantized):
        # the explicit fence: device -> host, off the pump thread
        k = np.asarray(k)
        v = np.asarray(v)
        if self.faults is not None:
            # chaos drills for the copy path: raise -> the page is
            # dropped and counted; corrupt -> a deterministic byte flip
            # lands in the stored payload
            k = self.faults.fire("tier_spill", k)
        ks = None if ks is None else np.asarray(ks, np.float32)
        vs = None if vs is None else np.asarray(vs, np.float32)
        if self.quantize and not prequantized:
            k, ks = _quantize_host(k)
            v, vs = _quantize_host(v)
        payload = {"k": k, "v": v, "ks": ks, "vs": vs}
        nb = _nbytes(payload)
        key = _kvc.block_hash(parent, block)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                # re-spill of a block we already hold (or a colliding
                # foreign chain — either way the stored entry wins)
                self._entries.move_to_end(key)
                return
            self._entries[key] = {"parent": parent, "block": block,
                                  "depth": int(depth), "payload": payload,
                                  "nbytes": nb}
            self._bytes += nb
            self.spills += 1
            dropped = self._shrink_locked()
            held, pages = self._bytes, len(self._entries)
        _flight.record("kvtier.spill", depth=int(depth), bytes=nb,
                       tier_bytes=held, tier_pages=pages)
        self._notify_drops(dropped)

    # -- disaggregated handoff export (pump thread waits; worker
    # thread fences) ---------------------------------------------------
    def export_pages(self, k, v, ks=None, vs=None, prequantized=False,
                     rids=None, timeout=30.0):
        """Fence a request's KV page slices to host for a prefill ->
        decode handoff (docs/serving.md § Disaggregated prefill/
        decode). `k`/`v` are functional device slices
        (L, KVH, pages, page, D) — valid snapshots however the pools
        are rewritten afterwards; the blocking np.asarray fence runs on
        the tier's copy thread, exactly like a spill. Encoding follows
        `self.quantize` (int8 + per-token scales unless the pool was
        already int8 — `prequantized=True` ships it verbatim).

        Synchronous from the caller's view: returns the host payload
        dict {k, v, ks, vs}, or raises whatever the copy path raised
        (including an armed `handoff_export` fault) — the engine
        degrades that request to local decode and releases nothing it
        did not build."""
        if self._worker is None:
            self._start_worker()
        job = _ExportJob(k, v, ks, vs, prequantized, rids)
        self._q.put(job)
        if not job.done.wait(timeout):
            raise TimeoutError(
                f"kvtier: handoff export did not land within {timeout}s")
        if job.error is not None:
            raise job.error
        return job.payload

    def _export(self, job):
        """Worker half of `export_pages`: explicit fence + encode.
        Nothing is indexed or ledgered — the payload belongs to the
        destination replica, not this tier."""
        k = np.asarray(job.k)
        if self.faults is not None:
            # chaos drills for the export path: raise -> the engine
            # keeps the request for local decode; corrupt -> a byte
            # flip lands in the shipped payload
            k = self.faults.fire("handoff_export", k, rids=job.rids)
        v = np.asarray(job.v)
        ks = None if job.ks is None else np.asarray(job.ks, np.float32)
        vs = None if job.vs is None else np.asarray(job.vs, np.float32)
        if self.quantize and not job.prequantized:
            k, ks = _quantize_host(k)
            v, vs = _quantize_host(v)
        return {"k": k, "v": v, "ks": ks, "vs": vs}

    def _shrink_locked(self):
        """Drop spilled entries until the ledger fits `tier_bytes` —
        deepest block first (ties: oldest), so a drop never orphans
        descendants and surviving roots keep matching. The pinned
        stash is never dropped (preemption correctness outranks the
        budget); it still counts, so heavy preemption pressure shrinks
        the spill side. Returns the dropped (key, entry) pairs so the
        caller can hand them to the fleet `on_drop` hook OUTSIDE the
        lock."""
        dropped = []
        while self._bytes > self.tier_bytes and self._entries:
            victim, depth = None, -1
            for key, e in self._entries.items():  # oldest-first scan
                if e["depth"] > depth:
                    victim, depth = key, e["depth"]
            e = self._entries.pop(victim)
            self._bytes -= e["nbytes"]
            self.drops += 1
            dropped.append((victim, e))
        return dropped

    def _notify_drops(self, dropped):
        """Feed budget-evicted entries to the fleet hook, lock already
        released. Fleet-originated entries (a peer spilled them here)
        never re-spill — without the flag two budget-pressured hosts
        would ping-pong the same page forever."""
        hook = self.on_drop
        if hook is None or not dropped:
            return
        local = [(k, e) for k, e in dropped if not e.get("fleet")]
        if local:
            hook(local)

    def flush(self, timeout=None):
        """Block until every queued spill has landed (tests/bench; the
        serving path never needs it — a still-in-flight page is simply
        a miss). `timeout` bounds the wait in seconds."""
        if self._q is None:
            return True
        if timeout is None:
            self._q.join()
            return True
        deadline = threading.Event()
        t = threading.Thread(target=lambda: (self._q.join(),
                                             deadline.set()),
                             daemon=True)
        t.start()
        return deadline.wait(timeout)

    # -- fleet page exchange (serving/fleet.py) ------------------------
    def insert(self, parent, block, depth, payload, fleet=False):
        """Index a host-resident page payload directly (no device
        fence): the landing half of a fleet page transfer — a peer
        shipped the page it owns, or a fetch-on-miss just pulled it.
        `fleet=True` marks the entry peer-originated so budget
        pressure drops it without re-spilling it back (`_notify_drops`
        skips the flag). Returns False when the tier is off or the
        key is already held."""
        if not self.enabled:
            return False
        block = tuple(int(t) for t in block)
        nb = _nbytes(payload)
        key = _kvc.block_hash(parent, block)
        dropped = []
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return False
            e = {"parent": parent, "block": block, "depth": int(depth),
                 "payload": payload, "nbytes": nb}
            if fleet:
                e["fleet"] = True
            self._entries[key] = e
            self._bytes += nb
            dropped = self._shrink_locked()
        self._notify_drops(dropped)
        return True

    def peek(self, key):
        """One spilled entry by chained hash — what a peer's
        fetch-on-miss asks this tier for over the fleet bulk channel.
        Touches recency; returns {parent, block, depth, payload} or
        None."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            self._entries.move_to_end(key)
            return {"parent": e["parent"], "block": e["block"],
                    "depth": e["depth"], "payload": e["payload"]}

    # -- lookup / restore accounting (pump thread) ---------------------
    def match(self, tokens, skip_tokens):
        """Continue the device cache's longest-prefix walk into the
        tier: re-derive the chained hashes of blocks 0..skip-1 (the
        device-matched prefix), then match tier entries block by block
        with raw (parent, block) verification — a hash collision falls
        through to a miss, never wrong KV. Capped one token short of
        len(tokens), same as the device match. Returns the matched
        entries' payloads in chain order.

        With a fleet `fetch_missing` hook attached, a walk that ends
        short of the cap continues through the hook (lock released —
        the fetch is a network round trip): whatever chain-order
        payloads the owning peer returns extend the match."""
        ps = self.page_size
        limit = (len(tokens) - 1) // ps
        skip = int(skip_tokens) // ps
        parent = _kvc._SEED
        out = []
        b = 0
        with self._lock:
            if not self._entries and self.fetch_missing is None:
                return out
            for b in range(limit):
                block = tuple(int(t) for t in tokens[b * ps:(b + 1) * ps])
                h = _kvc.block_hash(parent, block)
                if b >= skip:
                    e = self._entries.get(h)
                    if e is None or e["parent"] != parent \
                            or e["block"] != block:
                        break
                    out.append(e["payload"])
                    self._entries.move_to_end(h)
                parent = h
            else:
                b = limit
        hook = self.fetch_missing
        if hook is not None and skip <= b < limit:
            out.extend(hook(parent, b, tokens))
        return out

    def note_lookup(self, restored_pages):
        """Admission probed the tier; `restored_pages` pages actually
        made it back to the device (0 = miss)."""
        with self._lock:
            self.lookups += 1
            if restored_pages > 0:
                self.hits += 1
                self.restores += restored_pages
                self.tokens_reused += restored_pages * self.page_size

    # -- preemption offload stash (pinned; same ledger) ----------------
    def stash_put(self, key, payload, pages):
        """Park a preempted request's KV (verbatim — restore must be
        exact) under the shared ledger. Pinned: never dropped; spilled
        prefix pages make room instead."""
        nb = _nbytes(payload)
        dropped = []
        with self._lock:
            if key in self._stash:
                raise RuntimeError(f"kvtier: stash key {key!r} already "
                                   "held (double preemption?)")
            self._stash[key] = (payload, nb, int(pages))
            self._bytes += nb
            if self.enabled:
                dropped = self._shrink_locked()
        self._notify_drops(dropped)

    def stash_take(self, key):
        with self._lock:
            payload, nb, _ = self._stash.pop(key)
            self._bytes -= nb
        return payload

    def stash_discard(self, key):
        with self._lock:
            item = self._stash.pop(key, None)
            if item is not None:
                self._bytes -= item[1]

    # -- introspection -------------------------------------------------
    @property
    def host_bytes(self):
        with self._lock:
            return self._bytes

    @property
    def pages(self):
        """Host-resident KV pages: spilled prefix pages + stash pages."""
        with self._lock:
            return len(self._entries) + sum(p for _, _, p
                                            in self._stash.values())

    @property
    def hit_rate(self):
        with self._lock:
            return self.hits / self.lookups if self.lookups else 0.0

    def stats(self):
        with self._lock:
            stash_pages = sum(p for _, _, p in self._stash.values())
            return {"enabled": self.enabled,
                    "tier_bytes": self.tier_bytes,
                    "host_bytes": self._bytes,
                    "pages": len(self._entries) + stash_pages,
                    "spilled_pages": len(self._entries),
                    "stash_entries": len(self._stash),
                    "stash_pages": stash_pages,
                    "quantized": self.quantize,
                    "lookups": self.lookups, "hits": self.hits,
                    "hit_rate": (self.hits / self.lookups
                                 if self.lookups else 0.0),
                    "spills": self.spills, "restores": self.restores,
                    "drops": self.drops,
                    "copy_errors": self.copy_errors,
                    "tokens_reused": self.tokens_reused}
