"""Serving metrics: a counters/gauges/histograms registry with
Prometheus text exposition and a JSON snapshot API.

The registry is the single source for every number the serving runtime
publishes — TTFT, per-token latency, queue depth, batch occupancy,
preemption and page-allocation stats (reference: the predictor's
serving telemetry; vLLM exposes the same catalog over /metrics).
`EngineMetrics` is the engine-facing half: `ServingEngine.metrics`
duck-types against it, so `models/llama_serving.py` never imports this
module (no cycle — the engine works bare, the runtime instruments it;
the engine's only serving-package import is the host-side
`serving.kvcache` bookkeeping, which imports no model code back).
"""
from __future__ import annotations

import bisect
import os
import threading
import time

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "EngineMetrics", "DEFAULT_BUCKETS", "GAP_BUCKETS"]

# latency buckets in seconds: sub-ms CPU decode steps up to multi-second
# queued TTFTs all land in a populated bucket
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

# host-gap buckets: the time between device-step launches is tens of
# microseconds under the pipelined pump and a full device step plus
# bookkeeping under the synchronous one — finer left edge than the
# latency buckets so the reduction is visible in the histogram
GAP_BUCKETS = (2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
               0.01, 0.025, 0.05, 0.1, 0.25, 1.0)


def _escape_label_value(v):
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or the exposition line is
    invalid (and everything after it unparseable)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _label_suffix(labels):
    """`{k="v",...}` suffix in sorted-key order ('' when unlabeled).
    Keys sort so the same label set always renders one series name;
    values are escaped per the Prometheus text-format spec."""
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label_value(labels[k])}"'
                          for k in sorted(labels)) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name, help="", lock=None, labels=None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else {}
        self._suffix = _label_suffix(self.labels)
        self._lock = lock or threading.Lock()


class Counter(_Metric):
    """Monotonic count (Prometheus counter)."""
    kind = "counter"

    def __init__(self, name, help="", lock=None, labels=None):
        super().__init__(name, help, lock, labels)
        self._v = 0.0

    def inc(self, n=1.0):
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) < 0")
        with self._lock:
            self._v += n

    @property
    def value(self):
        with self._lock:
            return self._v

    def _render(self, out):
        out.append(f"{self.name}_total{self._suffix} {_fmt(self._v)}")

    def _snap(self):
        return {"type": "counter", "value": self._v}


class Gauge(_Metric):
    """Point-in-time value (Prometheus gauge)."""
    kind = "gauge"

    def __init__(self, name, help="", lock=None, labels=None):
        super().__init__(name, help, lock, labels)
        self._v = 0.0

    def set(self, v):
        with self._lock:
            self._v = float(v)

    def set_to_max(self, v):
        """Peak tracking: keep the high-water mark."""
        with self._lock:
            if v > self._v:
                self._v = float(v)

    def inc(self, n=1.0):
        with self._lock:
            self._v += n

    def dec(self, n=1.0):
        self.inc(-n)

    @property
    def value(self):
        with self._lock:
            return self._v

    def _render(self, out):
        out.append(f"{self.name}{self._suffix} {_fmt(self._v)}")

    def _snap(self):
        return {"type": "gauge", "value": self._v}


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus exposition shape);
    percentiles for the JSON snapshot are interpolated inside the
    landing bucket, which is exact enough for dashboards and tests."""
    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS, lock=None,
                 labels=None):
        super().__init__(name, help, lock, labels)
        self._bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self._bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._counts[bisect.bisect_left(self._bounds, v)] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def percentile(self, q):
        """Interpolated q-th percentile (q in [0, 100]); 0.0 when empty.
        A percentile landing in the overflow (+Inf) bucket returns the
        largest finite bucket edge — a LOWER bound, never `inf` (the
        snapshot flags it; see `percentile_overflow`)."""
        return self.percentile_overflow(q)[0]

    def percentile_overflow(self, q):
        """(value, in_overflow): `in_overflow` is True when the
        percentile fell in the +Inf bucket, making `value` (the largest
        finite bucket edge) a lower bound on the true percentile."""
        with self._lock:
            if self._count == 0:
                return 0.0, False
            target = self._count * q / 100.0
            seen = 0
            lo = 0.0
            for i, n in enumerate(self._counts):
                if i == len(self._bounds):
                    # overflow bucket: its finite edge is the previous
                    # bucket's upper bound — return it, flagged
                    return (self._bounds[-1] if self._bounds else lo), \
                        True
                hi = self._bounds[i]
                if seen + n >= target:
                    if n == 0:
                        return hi, False
                    return lo + (hi - lo) * (target - seen) / n, False
                seen += n
                lo = hi
            return lo, False

    def _render(self, out):
        cum = 0
        for i, b in enumerate(self._bounds):
            cum += self._counts[i]
            out.append(f'{self.name}_bucket{{le="{_fmt(b)}"}} {cum}')
        cum += self._counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        out.append(f"{self.name}_sum {_fmt(self._sum)}")
        out.append(f"{self.name}_count {self._count}")

    def _snap(self):
        cum, buckets = 0, {}
        for i, b in enumerate(self._bounds):
            cum += self._counts[i]
            buckets[_fmt(b)] = cum
        buckets["+Inf"] = cum + self._counts[-1]
        snap = {"type": "histogram", "count": self._count,
                "sum": self._sum, "buckets": buckets}
        for label, q in (("p50", 50), ("p90", 90), ("p99", 99)):
            v, overflow = self.percentile_overflow(q)
            snap[label] = v
            if overflow:
                # the true percentile is past the largest finite edge;
                # the reported value is a lower bound
                snap[f"{label}_lower_bound"] = True
        return snap


def _fmt(v):
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


_IMPORT_WALL_TIME = time.time()


def _process_start_time():
    """Unix timestamp the process started at (the standard
    `process_start_time_seconds` convention): /proc starttime ticks
    since boot plus the boot time, falling back to this module's
    import wall time where /proc is unavailable."""
    try:
        with open("/proc/self/stat") as f:
            stat = f.read()
        # field 22 (starttime, clock ticks since boot) counted after
        # the parenthesized comm — comm may contain spaces, so split
        # after the LAST ')'
        ticks = float(stat.rpartition(")")[2].split()[19])
        hz = float(os.sysconf("SC_CLK_TCK"))
        with open("/proc/stat") as f:
            for line in f:
                if line.startswith("btime "):
                    return float(line.split()[1]) + ticks / hz
    except (OSError, ValueError, IndexError):
        pass
    return _IMPORT_WALL_TIME


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Thread-safe get-or-create registry of named metrics.

    Counters and gauges optionally carry a small static label set
    (e.g. ``labels={"phase": "decode"}``); each distinct (name, label
    set) is its own series, keyed by the rendered ``name{k="v"}``
    string, and exposition emits one HELP/TYPE header per base name."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get(self, cls, name, help, labels=None, **kw):
        key = name + _label_suffix(labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help, lock=threading.Lock(),
                        labels=labels, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {key!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name, help="", labels=None):
        return self._get(Counter, name, help, labels=labels)

    def gauge(self, name, help="", labels=None):
        return self._get(Gauge, name, help, labels=labels)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS):
        # histograms stay unlabeled: bucket series already carry an
        # le= label and nothing in the stack needs labeled ones yet
        return self._get(Histogram, name, help, buckets=buckets)

    def render_prometheus(self):
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: (m.name, m._suffix))
        out = []
        prev = None
        for m in metrics:
            if m.name != prev:
                # one HELP/TYPE header per base name, shared by every
                # labeled series of that name
                if m.help:
                    out.append(f"# HELP {m.name} {m.help}")
                out.append(f"# TYPE {m.name} {m.kind}")
                prev = m.name
            with m._lock:
                m._render(out)
        return "\n".join(out) + "\n"

    def snapshot(self):
        """JSON-serializable dict of every metric's current state,
        keyed by name (plus the label suffix for labeled series)."""
        with self._lock:
            metrics = list(self._metrics.items())
        return {key: m._snap() for key, m in metrics}


class EngineMetrics:
    """The hook object `ServingEngine.metrics` duck-types against.

    The engine calls these from the thread driving `step()`; every
    method funnels into registry metrics, so a scrape from any other
    thread sees a consistent snapshot. `external_queue=True` (set by
    RequestScheduler) hands queue-depth ownership to the scheduler,
    whose queue sits in front of the engine's."""

    def __init__(self, registry=None, external_queue=False):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._external_queue = external_queue
        r = self.registry
        self.ttft = r.histogram(
            "pt_serving_ttft_seconds", "Submit-to-first-token latency.")
        self.tpot = r.histogram(
            "pt_serving_tpot_seconds", "Per-output-token latency.")
        self.e2e = r.histogram(
            "pt_serving_e2e_seconds", "Submit-to-completion latency.")
        self.step_seconds = r.histogram(
            "pt_serving_step_seconds",
            "Wall time of one engine step (prefill+decode/verify).")
        self.host_gap = r.histogram(
            "pt_step_host_gap_seconds",
            "Host wall time between consecutive device-step launches "
            "(decode/verify dispatch to the next dispatch) — the gap "
            "the device sits without a queued step program.",
            buckets=GAP_BUCKETS)
        self.pipeline_depth = r.gauge(
            "pt_pipeline_depth",
            "Device steps in flight beyond the one the host has "
            "consumed (1 = double-buffered pump, 0 = synchronous).")
        self.queue_depth = r.gauge(
            "pt_serving_queue_depth", "Requests waiting for a slot.")
        self.queue_depth_peak = r.gauge(
            "pt_serving_queue_depth_peak", "High-water queue depth.")
        self.batch_occupancy = r.gauge(
            "pt_serving_batch_occupancy",
            "Active slots / max_seqs at the last step.")
        self.active = r.gauge(
            "pt_serving_active_requests", "Requests holding a slot.")
        self.pages_free = r.gauge(
            "pt_serving_kv_pages_free", "KV pages in the free list.")
        self.pages_total = r.gauge(
            "pt_serving_kv_pages_total",
            "Allocatable KV pages (excludes the trash page).")
        self.prefill_tokens = r.gauge(
            "pt_serving_prefill_tokens", "Cumulative prefilled tokens.")
        # ragged vs bucketed dispatch accounting (ISSUE 11): how many
        # token rows were pure bucket padding vs real tokens served by
        # the unified ragged step — the padding waste the ragged entry
        # point exists to eliminate. Mirrored from engine ints via
        # on_step deltas (single-writer: the pump thread).
        self.pad_tokens = r.counter(
            "pt_pad_tokens",
            "Token rows dispatched as power-of-two bucket padding by "
            "the bucketed entry points (0 in ragged mode).")
        self.ragged_tokens = r.counter(
            "pt_ragged_tokens",
            "Real token rows served through the unified ragged step.")
        # lean epilogue accounting (ISSUE 12): unembed (lm_head) rows
        # actually computed vs rows the row-sparse epilogue skipped —
        # the (T, vocab) FLOPs/bytes that never ran. Same delta-mirror
        # pattern as the pad counters.
        self.logit_rows = r.counter(
            "pt_logit_rows",
            "lm_head logit rows computed by serving device programs.")
        self.logit_rows_skipped = r.counter(
            "pt_logit_rows_skipped",
            "Logit rows the lean row-sparse epilogue skipped (0 with "
            "PT_SERVE_LEAN=0).")
        self._tok_seen = {"pad_tokens": 0, "ragged_tokens": 0,
                          "logit_rows": 0, "logit_rows_skipped": 0}
        self.steps = r.counter(
            "pt_serving_device_steps", "Decode/verify device calls.")
        self.tokens = r.counter(
            "pt_serving_generated_tokens", "Output tokens emitted.")
        self.preemptions = r.counter(
            "pt_serving_preemptions", "Requests evicted mid-flight.")
        self.page_allocs = r.counter(
            "pt_serving_page_allocs", "KV pages handed out.")
        self.accepted = r.counter(
            "pt_serving_requests_accepted", "Requests admitted.")
        self.started = r.counter(
            "pt_serving_requests_started",
            "Requests fed to the engine (left the queue).")
        self.failed = r.counter(
            "pt_serving_requests_failed",
            "Requests failed by an engine error.")
        self.rejected = r.counter(
            "pt_serving_requests_rejected",
            "Requests refused by admission control (backpressure).")
        self.completed = r.counter(
            "pt_serving_requests_completed", "Requests finished.")
        self.cancelled = r.counter(
            "pt_serving_requests_cancelled", "Requests cancelled.")
        self.expired = r.counter(
            "pt_serving_requests_expired", "Requests past deadline.")
        # prefix KV cache (serving/kvcache.py): admission-time reuse
        self.prefix_lookups = r.counter(
            "pt_prefix_lookups",
            "Admissions that consulted the prefix cache.")
        self.prefix_hits = r.counter(
            "pt_prefix_hits", "Admissions that matched a cached prefix.")
        self.prefix_hit_rate = r.gauge(
            "pt_prefix_hit_rate",
            "Prefix-cache hit rate over admitted requests.")
        self.prefix_tokens_reused = r.counter(
            "pt_prefix_tokens_reused",
            "Prompt tokens served from cached KV pages instead of "
            "prefill compute.")
        self.prefix_evictions = r.counter(
            "pt_prefix_evictions",
            "Cached rc==0 pages reclaimed by allocation.")
        self.prefix_cached_pages = r.gauge(
            "pt_prefix_cached_pages",
            "Reclaimable rc==0 pages parked in the prefix cache.")
        # host-RAM KV tier (serving/kvtier.py): evicted prefix pages
        # demoted to host memory + the preemption offload stash, one
        # ledger. Counters mirror the tier's own rollups via on_step
        # deltas (spills land on the tier's copy thread; the mirror
        # runs on the pump, so every series stays single-writer).
        self.tier_spills = r.counter(
            "pt_prefix_tier_spills",
            "Evicted prefix pages spilled to the host-RAM tier.")
        self.tier_hits = r.counter(
            "pt_prefix_tier_hits",
            "Admissions that matched KV in the host tier.")
        self.tier_restores = r.counter(
            "pt_prefix_tier_restores",
            "KV pages restored host->device from the tier.")
        self.tier_drops = r.counter(
            "pt_prefix_tier_drops",
            "Host-tier pages dropped under the tier_bytes budget.")
        self.tier_copy_errors = r.counter(
            "pt_prefix_tier_copy_errors",
            "Spill copies that failed on the tier's copy thread (the "
            "page is dropped, the thread survives).")
        self.tier_host_bytes = r.gauge(
            "pt_tier_host_bytes",
            "Host RAM held by the KV tier (spilled pages + preemption "
            "stash).")
        self.tier_pages = r.gauge(
            "pt_tier_pages", "KV pages resident in the host tier.")
        self._tier_seen = {"spills": 0, "hits": 0, "restores": 0,
                           "drops": 0, "copy_errors": 0}
        # disaggregated prefill/decode (docs/serving.md § Disaggregated
        # prefill/decode): KV handoff traffic between role-specialized
        # replicas. Mirrored from engine ints via on_step deltas like
        # the tier counters (export runs on the pump thread, import on
        # the destination's pump — each replica's registry is private,
        # so every series stays single-writer); the router's /metrics
        # relabelling exposes them per replica for free.
        self.handoff_exports = r.counter(
            "pt_handoff_exports",
            "Requests whose KV pages were exported for a "
            "prefill->decode handoff.")
        self.handoff_imports = r.counter(
            "pt_handoff_imports",
            "Requests continued from an imported KV handoff payload.")
        self.handoff_bytes = r.counter(
            "pt_handoff_bytes",
            "KV payload bytes moved by handoffs (counted on both the "
            "export and import side).")
        self.handoff_failures = r.counter(
            "pt_handoff_failures",
            "Handoff exports/imports that failed and degraded to "
            "local decode / recompute-resume.")
        self.handoff_seconds = r.histogram(
            "pt_handoff_seconds",
            "Wall time of one handoff export or import (fence + "
            "encode/scatter, per side).")
        self._handoff_seen = {"handoff_exports": 0, "handoff_imports": 0,
                              "handoff_bytes": 0, "handoff_failures": 0}
        # crash recovery (serving/faults.py + scheduler warm restart):
        # restart cadence, requeue volume, and poison quarantines —
        # the numbers docs/reliability.md's runbook reads
        self.engine_restarts = r.counter(
            "pt_engine_restarts",
            "Warm restarts after an engine step exception (device "
            "state released, unstarted requests requeued).")
        self.restart_seconds = r.histogram(
            "pt_engine_restart_seconds",
            "Wall time of one warm restart: device-state release "
            "through requeue.")
        self.requests_requeued = r.counter(
            "pt_requests_requeued",
            "Requests requeued by a warm restart instead of failed.")
        self.poison_quarantined = r.counter(
            "pt_poison_quarantined",
            "Requests quarantined as poison after crashing K "
            "consecutive admitted steps.")
        # SLO / goodput plane (serving/timeline.py): judged per
        # completed request in the scheduler's finalize path from the
        # request's stitched timeline. Goodput is the Gemma-serving /
        # MPMD objective: tokens delivered INSIDE the latency target.
        self.total_tokens = r.counter(
            "pt_tokens",
            "Output tokens of completed requests (goodput denominator).")
        self.goodput_tokens = r.counter(
            "pt_goodput_tokens",
            "Output tokens of completed requests that met their SLO "
            "(requests with no SLO class count as delivered).")
        # pulse plane (observability/pulse.py) + process identity:
        # start time per the Prometheus convention, the self-cost of
        # one scrape/sample pass (the pulse plane's overhead is itself
        # observable), running-slot mix and per-priority queue depth —
        # the labeled series the pulse rings read trends from
        self.process_start_time = r.gauge(
            "pt_process_start_time_seconds",
            "Unix time the serving process started.")
        self.process_start_time.set(_process_start_time())
        self.scrape_self = r.gauge(
            "pt_scrape_self_seconds",
            "Wall time of the last metrics scrape / pulse sample pass "
            "(anomaly scan + snapshot + ring derivation).")
        self._slot_mix = {
            kind: r.gauge(
                "pt_serving_slots",
                "Occupied engine slots by phase of the request "
                "holding them.", labels={"kind": kind})
            for kind in ("prefill", "decode")}
        self._queue_priority = {}       # priority -> labeled gauge
        self.step_anomalies = r.counter(
            "pt_step_anomalies",
            "Serving steps flagged as stalls by the EWMA+MAD anomaly "
            "sentinel (each leaves an anomaly.step_stall flight record).")
        self.phase_seconds = {
            ph: r.histogram(
                f"pt_phase_{ph}_seconds",
                f"Wall seconds completed requests spent in the "
                f"'{ph}' phase of their timeline.")
            for ph in ("queued", "prefill", "decode", "preempted",
                       "handoff")}
        self._slo_attained = {}     # class -> labeled counter
        self._slo_violated = {}     # phase -> labeled counter

    # -- engine-facing hooks (called from the step()-driving thread) --
    def on_submit(self, engine):
        # with an external queue the scheduler already counted the
        # admission (engine.submit here is just the feed step)
        if not self._external_queue:
            self.accepted.inc()
            depth = len(engine._waiting)
            self.queue_depth.set(depth)
            self.queue_depth_peak.set_to_max(depth)

    def on_handoff(self, engine):
        """Mirror the engine's handoff counters. Runs inside on_step
        AND directly from the harvest/import sites: a prefill replica
        can go idle the moment its last request migrates away, with no
        further step to carry the delta onto /metrics."""
        seen = self._handoff_seen
        for attr, counter in (("handoff_exports", self.handoff_exports),
                              ("handoff_imports", self.handoff_imports),
                              ("handoff_bytes", self.handoff_bytes),
                              ("handoff_failures",
                               self.handoff_failures)):
            cur = getattr(engine, attr, 0)
            delta = cur - seen[attr]
            if delta > 0:
                counter.inc(delta)
                seen[attr] = cur
        # per-handoff durations drain on the pump thread (the same
        # thread that appends them), so a plain list is race-free
        times = getattr(engine, "_handoff_times", None)
        if times:
            for dt in times:
                self.handoff_seconds.observe(dt)
            del times[:]

    def on_step(self, engine, n_active):
        self.steps.inc()
        self.batch_occupancy.set(n_active / max(engine.max_seqs, 1))
        self.active.set(n_active)
        self.pages_free.set(len(engine._free))
        self.pages_total.set(engine.num_pages - 1)
        self.prefill_tokens.set(engine.prefill_tokens)
        seen = self._tok_seen
        for attr, counter in (("pad_tokens", self.pad_tokens),
                              ("ragged_tokens", self.ragged_tokens),
                              ("logit_rows", self.logit_rows),
                              ("logit_rows_skipped",
                               self.logit_rows_skipped)):
            cur = getattr(engine, attr, 0)
            delta = cur - seen[attr]
            if delta > 0:
                counter.inc(delta)
                seen[attr] = cur
        self.on_handoff(engine)
        pc = getattr(engine, "prefix_cache", None)
        if pc is not None:
            self.prefix_cached_pages.set(pc.cached_pages)
        tier = getattr(engine, "host_tier", None)
        if tier is not None:
            st = tier.stats()
            self.tier_host_bytes.set(st["host_bytes"])
            self.tier_pages.set(st["pages"])
            seen = self._tier_seen
            for name, counter in (("spills", self.tier_spills),
                                  ("hits", self.tier_hits),
                                  ("restores", self.tier_restores),
                                  ("drops", self.tier_drops),
                                  ("copy_errors", self.tier_copy_errors)):
                delta = st[name] - seen[name]
                if delta > 0:
                    counter.inc(delta)
                    seen[name] = st[name]
        if not self._external_queue:
            depth = len(engine._waiting)
            self.queue_depth.set(depth)
            self.queue_depth_peak.set_to_max(depth)

    def observe_ttft(self, dt):
        self.ttft.observe(dt)

    def observe_host_gap(self, dt):
        """Engine hook: wall time from the previous decode/verify
        dispatch returning to this one starting."""
        self.host_gap.observe(dt)

    def set_pipeline_depth(self, depth):
        self.pipeline_depth.set(depth)

    def observe_tpot(self, dt):
        self.tpot.observe(dt)

    def on_tokens(self, n):
        self.tokens.inc(n)

    def on_preempt(self, policy):
        self.preemptions.inc()

    def on_page_alloc(self, n):
        self.page_allocs.inc(n)

    def on_finish(self, req, dt=None):
        self.completed.inc()
        if dt is not None:
            self.e2e.observe(dt)

    def on_cancel(self, where):
        self.cancelled.inc()

    def on_prefix_lookup(self, cached_tokens):
        """One admitted request consulted the prefix cache;
        cached_tokens == 0 is a miss."""
        self.prefix_lookups.inc()
        if cached_tokens > 0:
            self.prefix_hits.inc()
            self.prefix_tokens_reused.inc(cached_tokens)
        lk = self.prefix_lookups.value
        self.prefix_hit_rate.set(self.prefix_hits.value / lk if lk
                                 else 0.0)

    def on_prefix_evict(self, n=1):
        self.prefix_evictions.inc(n)

    # -- scheduler-facing hooks --
    def observe_step(self, dt):
        self.step_seconds.observe(dt)

    def on_reject(self):
        self.rejected.inc()

    def on_start(self):
        """A queued request was fed to the engine."""
        self.started.inc()

    def on_fail(self):
        """A request was failed by an engine error (the router's
        failover trigger)."""
        self.failed.inc()

    def on_restart(self, dt):
        """One warm restart completed (device-state release through
        requeue) in `dt` seconds."""
        self.engine_restarts.inc()
        self.restart_seconds.observe(dt)

    def on_requeue(self, n):
        """`n` requests were requeued instead of failed."""
        self.requests_requeued.inc(n)

    def on_poison(self):
        """A request was quarantined as poison."""
        self.poison_quarantined.inc()

    def on_expire(self):
        self.expired.inc()

    def observe_phases(self, phases):
        """One completed request's phase -> seconds breakdown."""
        for ph, dt in phases.items():
            h = self.phase_seconds.get(ph)
            if h is not None:
                h.observe(dt)

    def on_request_tokens(self, n):
        """Output tokens of one completed request (goodput
        denominator; `on_goodput` adds the numerator)."""
        self.total_tokens.inc(n)

    def on_goodput(self, n):
        """`n` tokens were delivered inside their latency objective
        (or carried no objective)."""
        self.goodput_tokens.inc(n)

    def on_slo_attained(self, slo):
        c = self._slo_attained.get(slo)
        if c is None:
            c = self.registry.counter(
                "pt_slo_attained",
                "Completed requests that met their SLO class targets.",
                labels={"slo": slo})
            self._slo_attained[slo] = c
        c.inc()

    def on_slo_violated(self, phase):
        c = self._slo_violated.get(phase)
        if c is None:
            c = self.registry.counter(
                "pt_slo_violated",
                "Completed requests that missed their SLO, attributed "
                "to the dominant phase of the violated budget.",
                labels={"phase": phase})
            self._slo_violated[phase] = c
        c.inc()

    def on_step_anomaly(self, n=1):
        self.step_anomalies.inc(n)

    def set_queue_depth(self, depth):
        self.queue_depth.set(depth)
        self.queue_depth_peak.set_to_max(depth)

    def set_queue_depths(self, by_priority):
        """Per-priority queue depths (labeled gauges) alongside the
        total `set_queue_depth` already books."""
        for priority, depth in by_priority.items():
            g = self._queue_priority.get(priority)
            if g is None:
                g = self.registry.gauge(
                    "pt_serving_queue_depth_priority",
                    "Requests waiting for a slot, by priority class.",
                    labels={"priority": priority})
                self._queue_priority[priority] = g
            g.set(depth)

    def set_slot_mix(self, prefill, decode):
        """Running-slot mix sampled by the pump each step."""
        self._slot_mix["prefill"].set(prefill)
        self._slot_mix["decode"].set(decode)

    def observe_scrape_self(self, dt):
        """Self-cost of one scrape/sample pass (scrape-thread side)."""
        self.scrape_self.set(dt)
