"""One serving replica: an engine + scheduler pair behind a
transport-agnostic surface the router dispatches to.

A `Replica` owns one `ServingEngine` (its KV pool, prefix cache, pump
state) wrapped in one `RequestScheduler` (its bounded queue and pump
thread) plus a PRIVATE `MetricsRegistry` — nothing is shared between
replicas, so N replicas are N independent failure domains in one
process. The surface the router uses is deliberately small and
carries no in-process types in its *semantics* (submit parameters and
stats are plain data; only the returned request handle is local), so
a future multi-host replica can implement the same methods over the
existing rpc/collective layer without touching the router:

  * `submit(prompt_ids, **params)` — admit-or-refuse now
    (`BackpressureError` / `SchedulerClosedError` pass through);
  * `stats()` / `load()` — queue depth, occupancy, and the
    scheduler's monotonic request ledger (started/completed/failed),
    which is what health tracking diffs;
  * `ready()` — readiness (False while paused or draining), the
    /readyz signal an external LB would consume;
  * `pause()/resume()/shutdown(drain=)` — rolling-restart hooks;
  * `kill()` — fault injection for failover drills and tests.

The engine arrives as a constructor argument: this module imports no
model code (the serving package stays cycle-free and cheap).
"""
from __future__ import annotations

from .faults import FaultPlan
from .metrics import MetricsRegistry
from .scheduler import RequestScheduler

__all__ = ["Replica", "ReplicaKilledError", "build_replicas"]


class ReplicaKilledError(RuntimeError):
    """Injected engine failure (Replica.kill): every subsequent step
    raises, so the scheduler's crash recovery runs — requeues, then
    quarantine/breaker — and the router's failover path takes over."""


class Replica:
    """In-process replica: one engine + scheduler + metrics registry.

    `replica_id` is the stable identity used for consistent-hash ring
    placement, the `replica=` label on aggregated /metrics, and
    flight-recorder events. Extra keyword arguments (`poison_after`,
    `max_restarts`, `restart_window_s`, ...) pass through to the
    scheduler — per-replica recovery thresholds for chaos drills.

    `role` specializes the replica for disaggregated serving
    (docs/serving.md § Disaggregated prefill/decode): "prefill"
    replicas take new requests and hand their KV off once the prompt
    is prefilled; "decode" replicas only continue imported requests;
    "both" — the default — serves end-to-end exactly as before (no
    handoff machinery runs, zero cost). The role is advisory identity
    the ROUTER enforces at dispatch; the engine itself stays
    role-agnostic.
    """

    ROLES = ("prefill", "decode", "both")

    # fleet-mode host tag (serving/fleet.py sets it on the worker):
    # None on a plain in-process replica, so single-host metrics and
    # /debug payloads stay byte-identical
    host = None

    def __init__(self, replica_id, engine, *, max_queue=64,
                 metrics=None, idle_poll_s=0.02, pipeline=None,
                 role="both", **sched_kw):
        self.replica_id = str(replica_id)
        if role not in self.ROLES:
            raise ValueError(
                f"role={role!r}: want one of {self.ROLES}")
        self.role = role
        self.engine = engine
        registry = metrics if metrics is not None else MetricsRegistry()
        self.scheduler = RequestScheduler(engine, max_queue=max_queue,
                                          metrics=registry,
                                          idle_poll_s=idle_poll_s,
                                          pipeline=pipeline, **sched_kw)

    # -- identity / introspection -------------------------------------
    @property
    def registry(self):
        return self.scheduler.registry

    @property
    def page_size(self):
        """KV page size — the router's affinity keys hash block-aligned
        prompt prefixes at this granularity (same chained block-hash
        scheme the replica's own prefix cache indexes by)."""
        return int(self.engine.page_size)

    @property
    def max_queue(self):
        return self.scheduler.max_queue

    def stats(self):
        st = self.scheduler.stats()
        st["replica_id"] = self.replica_id
        st["role"] = self.role
        st["ready"] = self.ready()
        if self.host is not None:
            st["host"] = self.host
        return st

    def prefill_eligible(self):
        """May take NEW requests (fresh prompts to prefill)."""
        return self.role in ("prefill", "both")

    def decode_eligible(self):
        """May continue an imported (or locally prefilled) decode."""
        return self.role in ("decode", "both")

    def load(self):
        """Queued + in-flight requests — the least-loaded spill order
        sorts on this. One lock acquisition, cheap enough per
        dispatch."""
        st = self.scheduler.stats()
        return st["queued"] + st["inflight"] + st["active"]

    def recent_requests(self, n=50):
        """Recent terminal requests with their stitched timelines —
        plain JSON-shaped data, so a multi-host replica can ship it
        over the rpc layer unchanged (/debug/requests aggregation)."""
        return self.scheduler.recent_requests(n)

    def ready(self):
        return self.scheduler.readiness()[0]

    # -- dispatch ------------------------------------------------------
    def submit(self, prompt_ids, **params):
        """Admit-or-refuse now; returns the scheduler's request
        handle. BackpressureError (queue full) and SchedulerClosedError
        (draining) propagate — the router turns those into spill /
        re-dispatch decisions."""
        return self.scheduler.submit(prompt_ids, **params)

    # -- operational controls -----------------------------------------
    def pause(self):
        self.scheduler.pause()

    def resume(self):
        self.scheduler.resume()

    def drain(self, timeout=None):
        return self.scheduler.drain(timeout=timeout)

    def shutdown(self, drain=True, timeout=None):
        return self.scheduler.shutdown(drain=drain, timeout=timeout)

    def kill(self, exc=None):
        """Fault injection: one FaultPlan rule among many — an
        infinite `step_launch:raise` armed on the engine's plan, so
        every device step (sync, pipelined, and spec dispatch all fire
        the same point) raises and the scheduler's crash recovery
        runs: requeues burn through the poison/breaker thresholds and
        the router fails the requests over to a healthy replica. A
        real crash (OOM, device loss) takes the identical code path
        because the pump converts ANY step exception into a warm
        restart."""
        err = exc if exc is not None else ReplicaKilledError(
            f"replica {self.replica_id}: killed (fault injection)")
        plan = self.engine.faults
        if plan is None:
            plan = self.engine.faults = FaultPlan()
        plan.add("step_launch", "raise", count=None, exc=err,
                 label=f"kill:{self.replica_id}")

    def revive(self):
        """Undo `kill()`: remove the kill rule and close the crash-
        loop breaker — the 'replica restarted' half of a failover
        drill (the scheduler's recovery already left the engine's
        slots and pages clean)."""
        plan = self.engine.faults
        if plan is not None:
            plan.remove(f"kill:{self.replica_id}")
        # tests may also have installed direct step overrides
        self.engine.__dict__.pop("step", None)
        self.engine.__dict__.pop("step_launch", None)
        self.scheduler.reset_breaker()

    def __repr__(self):
        return f"Replica({self.replica_id!r})"


def build_replicas(engine_factory, n, *, max_queue=64, prefix="r",
                   idle_poll_s=0.02, pipeline=None, roles=None,
                   **sched_kw):
    """N independent replicas from an engine factory. The factory is
    called once per replica — each gets its own params reference but
    its own KV pool, prefix cache, scheduler, and metrics registry
    (`engine_factory(i) -> ServingEngine`). `roles` is an optional
    per-replica role list (short lists pad with "both") for a
    disaggregated prefill/decode topology."""
    roles = list(roles or [])
    roles += ["both"] * (int(n) - len(roles))
    return [Replica(f"{prefix}{i}", engine_factory(i),
                    max_queue=max_queue, idle_poll_s=idle_poll_s,
                    pipeline=pipeline, role=roles[i], **sched_kw)
            for i in range(int(n))]
