"""Scale-out serving tier: prefix-affinity router over a replica pool.

One pump thread owning one engine is the single-host ceiling; this
module fans `/v1/completions` traffic across N independent replicas
(`serving/replica.py` — engine + scheduler + metrics per replica) from
any number of frontend threads:

  * **Prefix affinity.** The dispatch key is the chained block hash of
    the longest block-aligned prompt prefix — the SAME hash scheme the
    replicas' prefix caches index by (`serving/kvcache.py`), so two
    prompts that would share cached KV pages hash to the same key. The
    key picks a replica on a consistent-hash ring (virtual nodes), so
    a hot system prompt keeps landing on the replica that already
    holds its pages, and adding/draining a replica only re-homes the
    keys that map to it.
  * **Least-loaded spill.** When the affinity target refuses admission
    (`BackpressureError`) or is out of rotation, the request spills to
    the least-loaded healthy replica instead of queueing behind the
    hot spot. All replicas full → the BackpressureError propagates
    (HTTP 429, client owns the retry).
  * **Health / circuit breaker.** Per-replica consecutive-failure
    counts drive a breaker: `ok → open` after `unhealthy_after`
    consecutive failed requests (no new dispatches), `open →
    half_open` after `probe_after_s` (ONE probe request), probe
    success closes the breaker, probe failure re-opens it.
  * **Failover.** A request that its replica failed before emitting
    any output (queued-but-unstarted when the engine died) is
    transparently re-dispatched to another replica — same request id,
    same trace id, bounded by the set of remaining replicas. Outputs
    are token-identical to an undisturbed run because generation is
    deterministic given the request parameters.
  * **Graceful drain.** `drain_replica()` flips readiness off (ring
    exit + scheduler close), lets running work finish, then removes
    the replica — the rolling-restart primitive. Draining is refused
    when it would leave a non-empty pool with no prefill-eligible
    replica (queued requests would strand behind decode-only
    replicas); draining the very last replica stays allowed.
  * **Disaggregated prefill/decode.** Replicas carry a `role`
    (`replica.py`): only prefill-eligible ones ("prefill"/"both") own
    ring points and take new requests. A dispatch to a pure prefill
    replica arms `kv_export` whenever a decode-eligible replica is in
    rotation; when the request terminates in state "handoff" the
    consumer-side handle migrates it — `_migrate` re-submits the
    KVHandoff payload to the least-loaded decode replica ("decode"
    first, then "both", then the source itself as the never-dropped
    fallback) and the handle swaps underneath the caller invisibly.

Everything is host-side stdlib; the router never touches an engine
directly (TPL004: no engine/device calls under the router lock — the
lock only guards routing state; replica submits happen outside it).
`router.dispatch` / `router.failover` flight-recorder events carry
each request's trace id across the dispatch hop.
"""
from __future__ import annotations

import bisect
import itertools
import threading
import time

from ..observability import flight_recorder as _flight
from .kvcache import _SEED, block_hash
from .metrics import MetricsRegistry
from .scheduler import (BackpressureError, SchedulerClosedError,
                        SchedulerError)

__all__ = ["Router", "RouterRequest", "prefix_key"]


def prefix_key(tokens, page_size):
    """Routing key for a prompt: the chained block hash
    (`kvcache.block_hash`) of its longest block-aligned prefix, capped
    one token short exactly like `PrefixCache.match` — equal keys mean
    the replicas' caches would index the same page chain. Prompts with
    no full block hash their raw tokens so identical short prompts
    still co-locate. Returns (key, n_blocks)."""
    ps = int(page_size)
    toks = tuple(int(t) for t in tokens)
    n_blocks = max(len(toks) - 1, 0) // ps if ps > 0 else 0
    parent = _SEED
    for b in range(n_blocks):
        parent = block_hash(parent, toks[b * ps:(b + 1) * ps])
    if n_blocks == 0:
        parent = block_hash(parent, toks)
    return parent, n_blocks


class _HashRing:
    """Consistent-hash ring with virtual nodes. Not thread-safe — the
    router mutates it under its lock."""

    def __init__(self, vnodes=64):
        self.vnodes = int(vnodes)
        self._points = []            # sorted [(point, rid)]

    def add(self, rid):
        for i in range(self.vnodes):
            bisect.insort(self._points, (hash((rid, i)), rid))

    def remove(self, rid):
        self._points = [(p, r) for p, r in self._points if r != rid]

    def lookup(self, key):
        """Replica owning `key`: first point clockwise of it."""
        pts = self._points
        if not pts:
            return None
        i = bisect.bisect_left(pts, (key,))
        return pts[i % len(pts)][1]


class _ReplicaState:
    """Router-side view of one replica: circuit-breaker state plus
    dispatch accounting. Mutated only under the router lock."""

    __slots__ = ("replica", "state", "failures", "opened_at",
                 "probe_at", "dispatches", "failovers_in")

    def __init__(self, replica):
        self.replica = replica
        self.state = "ok"            # ok | open | half_open | draining
        self.failures = 0            # consecutive failed requests
        self.opened_at = 0.0
        self.probe_at = 0.0
        self.dispatches = 0
        self.failovers_in = 0        # requests failed over TO this one


class RouterRequest:
    """Caller-facing handle over whichever replica currently owns the
    request. Duck-types the `ServingRequest` surface the HTTP server
    consumes (`rid/req/state/error/output/trace_id`, `stream()`,
    `result()`, `cancel()`); on a replica failure BEFORE any output it
    re-dispatches to another replica transparently, so rolling
    restarts and engine crashes never surface for queued work."""

    def __init__(self, router, sr, replica_id, prompt_ids, params, key):
        self._router = router
        self._sr = sr                # current underlying ServingRequest
        self.replica_id = replica_id
        self._prompt = list(prompt_ids)
        # resubmit with the identical parameters + ids: failover output
        # must be what the original dispatch would have produced
        self._params = dict(params, rid=sr.rid, trace_id=sr.trace_id)
        self._key = key
        self._tried = [replica_id]
        self.failovers = 0
        self._reported = False

    # -- delegation to the current underlying request -----------------
    @property
    def rid(self):
        return self._sr.rid

    @property
    def req(self):
        return self._sr.req

    @property
    def state(self):
        return self._sr.state

    @property
    def error(self):
        return self._sr.error

    @property
    def output(self):
        return self._sr.output

    @property
    def trace_id(self):
        return self._sr.trace_id

    @property
    def priority(self):
        return self._sr.priority

    @property
    def t_first_token(self):
        return self._sr.t_first_token

    @property
    def timeline(self):
        """The CURRENT owner's timeline — after a migration this is
        the decode replica's, i.e. the full stitched ledger."""
        return self._sr.timeline

    @property
    def slo(self):
        return self._sr.slo

    @property
    def slo_attained(self):
        return self._sr.slo_attained

    @property
    def violated_phase(self):
        return self._sr.violated_phase

    def cancel(self):
        return self._sr.cancel()

    # -- failover machinery -------------------------------------------
    def _report(self):
        """Feed the terminal state into the router's health tracking
        exactly once per underlying dispatch."""
        if not self._reported:
            self._reported = True
            self._router._note_result(self.replica_id, self._sr.state)

    def _failed_unstarted(self):
        """Replica failed this request before the CONSUMER saw any
        bytes — the safe-to-replay case. Generated-but-unconsumed
        tokens (e.g. a warm restart's requeue cycles before the crash-
        loop breaker gave up) don't block failover: generation is
        deterministic for the given parameters, and a failed request
        never publishes further chunks, so a re-dispatch is token-
        identical to an undisturbed run."""
        return self._sr.state == "failed" and \
            not getattr(self._sr, "_streamed", False)

    def _failover_or_raise(self, err):
        self._report()
        nxt = self._router._redispatch(self)
        if nxt is None:
            raise err
        rid, sr = nxt
        self._tried.append(rid)
        self.replica_id = rid
        self._sr = sr
        self._reported = False
        self.failovers += 1

    def _continue_handoff(self):
        """The current replica finished its PREFILL half (terminal
        state "handoff", KVHandoff payload attached): migrate the
        request to a decode replica and swap the underlying handle.
        The decode submit presets the already-published output, so
        streaming resumes exactly where the prefill replica stopped."""
        self._report()               # handoff == success for health
        rid, sr = self._router._migrate(self)
        self._tried.append(rid)
        self.replica_id = rid
        self._sr = sr
        self._reported = False

    # -- consumption ---------------------------------------------------
    def stream(self, timeout=None):
        """Yield token chunks; a pre-first-token replica death is
        retried on another replica invisibly, and a prefill->decode
        handoff continues on the decode replica mid-stream. Once a
        chunk has been yielded the stream is never replayed (the
        caller already has tokens) — a later failure raises."""
        sent = 0
        while True:
            try:
                for chunk in self._sr.stream(timeout=timeout):
                    sent += 1
                    yield chunk
                if self._sr.state == "handoff":
                    self._continue_handoff()
                    continue
                self._report()
                return
            except Exception as e:  # noqa: BLE001 — terminal-state errors
                if sent == 0 and self._failed_unstarted():
                    self._failover_or_raise(e)
                    continue
                self._report()
                raise

    def result(self, timeout=None):
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            left = None if deadline is None \
                else max(deadline - time.monotonic(), 0.001)
            try:
                out = self._sr.result(timeout=left)
            except TimeoutError:
                raise
            except Exception as e:  # noqa: BLE001 — terminal-state errors
                if self._failed_unstarted():
                    self._failover_or_raise(e)
                    continue
                self._report()
                raise
            if self._sr.state == "handoff":
                self._continue_handoff()
                continue
            self._report()
            return out


class Router:
    """Replica pool + dispatcher. Duck-types the scheduler surface the
    HTTP server mounts (`submit/stats/readiness/shutdown/
    render_prometheus/metrics_snapshot`), so
    `ServingServer(Router(...))` is the whole wiring.

    The router lock guards ONLY routing state (ring, breaker states,
    counters); replica submits and stats reads happen outside it, so a
    slow replica never serializes dispatch to the others.
    """

    def __init__(self, replicas, *, policy="affinity", vnodes=64,
                 unhealthy_after=2, probe_after_s=1.0, metrics=None,
                 faults=None, fleet=None):
        if policy not in ("affinity", "round_robin"):
            raise ValueError(
                f"policy={policy!r}: use 'affinity' or 'round_robin'")
        # optional serving.faults.FaultPlan: the `router_dispatch`
        # point fires once per submit, before replica selection
        self.faults = faults
        # optional serving.fleet.FleetPlane: attaching it lights up
        # the /debug/fleet/* endpoints (cross-host stitched trace,
        # merged flight rings) on the server mounting this router
        self.fleet = fleet
        self._lock = threading.Lock()
        self._replicas = {}          # rid -> _ReplicaState (ordered)
        self._ring = _HashRing(vnodes)
        self._policy = policy
        self._rr = itertools.count()
        self.unhealthy_after = int(unhealthy_after)
        self.probe_after_s = float(probe_after_s)
        self.page_size = None
        self.registry = metrics if isinstance(metrics, MetricsRegistry) \
            else MetricsRegistry()
        r = self.registry
        self.dispatches = r.counter(
            "pt_router_dispatches", "Requests dispatched to a replica.")
        self.affinity_hits = r.counter(
            "pt_router_affinity_hits",
            "Dispatches that landed on the prefix-affinity target.")
        self.spills = r.counter(
            "pt_router_spills",
            "Dispatches diverted off the affinity target "
            "(backpressure or health).")
        self.probes = r.counter(
            "pt_router_probes", "Half-open circuit-breaker probes.")
        self.failovers = r.counter(
            "pt_router_failovers",
            "Requests re-dispatched after a replica failed them "
            "before any output.")
        self.rejects = r.counter(
            "pt_router_rejects",
            "Requests refused because every replica was full or out "
            "of rotation.")
        self.unhealthy_transitions = r.counter(
            "pt_router_unhealthy_transitions",
            "Circuit-breaker ok->open transitions.")
        self.handoffs = r.counter(
            "pt_router_handoffs",
            "Requests migrated prefill->decode after a KV export.")
        self.replicas_gauge = r.gauge(
            "pt_router_replicas", "Registered replicas.")
        self.ready_gauge = r.gauge(
            "pt_router_replicas_ready", "Replicas accepting dispatches.")
        # per-replica exposition cost (satellite of the timeline plane):
        # one labeled gauge per replica so a slow scrape names its
        # culprit; created lazily as replicas join
        self._scrape_gauges = {}
        for rep in replicas:
            self.add_replica(rep)

    # -- pool membership ----------------------------------------------
    def add_replica(self, replica):
        """Register a replica and give it ring ownership (rolling
        restarts re-add here after drain_replica removed). Decode-only
        replicas never own ring points — new prompts can't start
        there; they receive work only via `_migrate`."""
        rid = replica.replica_id
        ps = int(replica.page_size)
        with self._lock:
            if rid in self._replicas:
                raise ValueError(f"router: duplicate replica id {rid!r}")
            if self.page_size is None:
                self.page_size = ps
            elif ps != self.page_size:
                raise ValueError(
                    f"router: replica {rid!r} page_size={ps} != "
                    f"{self.page_size} — affinity keys would diverge "
                    "from the replicas' prefix caches")
            self._replicas[rid] = _ReplicaState(replica)
            if replica.prefill_eligible():
                self._ring.add(rid)
            self.replicas_gauge.set(len(self._replicas))

    def replica(self, rid):
        with self._lock:
            st = self._replicas.get(rid)
        return None if st is None else st.replica

    def affinity_target(self, prompt_ids):
        """Replica id the consistent-hash ring names for this prompt's
        prefix key, ignoring health — where the request WOULD go on a
        healthy pool (observability + tests)."""
        key, _ = prefix_key(prompt_ids, self.page_size or 1)
        with self._lock:
            return self._ring.lookup(key)

    @property
    def replica_ids(self):
        with self._lock:
            return list(self._replicas)

    def drain_replica(self, rid, timeout=None, remove=True):
        """Rolling-restart primitive: take `rid` out of rotation
        (readiness flips false immediately), let in-flight and queued
        work finish, then drop it from the pool. Returns True when the
        replica's pump exited within `timeout`.

        Refused (ValueError) when the replicas left behind form a
        NON-EMPTY pool with no prefill-eligible member — new requests
        would strand behind decode-only replicas that can never start
        them. Draining the very last replica stays allowed: an empty
        pool rejects crisply with SchedulerClosedError instead of
        silently queueing."""
        with self._lock:
            st = self._replicas.get(rid)
            if st is None:
                raise KeyError(f"router: no replica {rid!r}")
            rest = [s for r, s in self._replicas.items()
                    if r != rid and s.state != "draining"]
            if rest and not any(s.replica.prefill_eligible()
                                for s in rest):
                raise ValueError(
                    f"router: draining {rid!r} would leave no "
                    "prefill-eligible replica in rotation — new "
                    "requests would strand; drain a decode replica "
                    "first or add a 'prefill'/'both' replica")
            st.state = "draining"
            self._ring.remove(rid)
        _flight.record("router.drain", replica=rid)
        ok = st.replica.shutdown(drain=True, timeout=timeout)
        if remove:
            with self._lock:
                self._replicas.pop(rid, None)
                self.replicas_gauge.set(len(self._replicas))
        return ok

    # -- dispatch ------------------------------------------------------
    def submit(self, prompt_ids, *, priority="normal", ttl_s=None,
               trace_id=None, rid=None, **params):
        """Dispatch by prefix affinity with least-loaded spill; returns
        a RouterRequest. Raises BackpressureError when every eligible
        replica refused admission, SchedulerClosedError when none is in
        rotation, ValueError for a request no engine could run (the
        first candidate validates it)."""
        if self.faults is not None:
            self.faults.fire("router_dispatch",
                             rids=None if rid is None else [str(rid)])
        key, n_blocks = prefix_key(prompt_ids, self.page_size or 1)
        plan = self._plan(key)
        kw = dict(params, priority=priority, ttl_s=ttl_s,
                  trace_id=trace_id)
        last_err = None
        for target, kind in plan:
            with self._lock:
                st = self._replicas.get(target)
            if st is None:           # removed between plan and dispatch
                continue
            try:
                # kv_export is decided PER DISPATCH (never stored in
                # the replay params): a failover or topology change
                # must re-decide against the replica that actually
                # takes the request
                sr = st.replica.submit(
                    prompt_ids, rid=rid,
                    kv_export=self._kv_export_for(target), **kw)
            except BackpressureError as e:
                last_err = e
                continue
            except SchedulerClosedError as e:
                last_err = e
                continue
            self._note_dispatch(target, kind, sr, n_blocks)
            return RouterRequest(self, sr, target, prompt_ids, kw, key)
        self.rejects.inc()
        if last_err is not None:
            raise last_err
        raise SchedulerClosedError(
            "router: no replica in rotation (all draining or removed)")

    def _plan(self, key):
        """Dispatch order: the affinity target first (consistent-hash
        owner of the key; `round_robin` policy rotates instead), then
        every other eligible replica by ascending load — the spill
        order. Half-open probes ride the same plan with kind
        'probe'."""
        now = time.monotonic()
        with self._lock:
            if not self._replicas:
                raise SchedulerClosedError("router: no replicas")
            if self._policy == "affinity":
                primary = self._ring.lookup(key)
            else:
                rids = [i for i, st in self._replicas.items()
                        if st.state != "draining"
                        and st.replica.prefill_eligible()]
                primary = rids[next(self._rr) % len(rids)] if rids \
                    else None
            # decode-only replicas never take NEW requests — they are
            # fed exclusively through _migrate (KV handoff import)
            cands = [(i, st.replica, self._eligibility_locked(st, now))
                     for i, st in self._replicas.items()
                     if st.replica.prefill_eligible()]
        plan = []
        spill = []
        for i, rep, elig in cands:
            if elig is None:
                continue
            if i == primary:
                kind = "probe" if elig == "probe" else (
                    "affinity" if self._policy == "affinity" else "rr")
                plan.append((i, kind))
            else:
                # load() is one scheduler-lock hop per replica; done
                # OUTSIDE the router lock so dispatch never serializes
                # on a slow replica
                spill.append((rep.load(), i,
                              "probe" if elig == "probe" else "spill"))
        spill.sort(key=lambda t: t[0])
        plan.extend((i, kind) for _, i, kind in spill)
        return plan

    def _eligibility_locked(self, st, now):
        """None (skip), 'ok', or 'probe' (breaker half-open trial)."""
        if st.state == "draining":
            return None
        if st.state == "ok":
            return "ok"
        if st.state == "half_open":
            # one probe at a time; a probe that never reports back
            # (abandoned handle) unblocks after another cooldown
            if now - st.probe_at >= self.probe_after_s:
                return "probe"
            return None
        # open: cooled down -> offer one probe
        if now - st.opened_at >= self.probe_after_s:
            return "probe"
        return None

    def _note_dispatch(self, rid, kind, sr, n_blocks):
        with self._lock:
            st = self._replicas.get(rid)
            if st is not None:
                st.dispatches += 1
                if kind == "probe":
                    st.state = "half_open"
                    st.probe_at = time.monotonic()
        self.dispatches.inc()
        if kind == "affinity":
            self.affinity_hits.inc()
        elif kind == "probe":
            self.probes.inc()
        elif kind == "spill":
            self.spills.inc()
        # "rr" (round_robin primary) counts only as a dispatch
        _flight.record("router.dispatch", rid=str(sr.rid),
                       trace_id=sr.trace_id, replica=rid, route=kind,
                       prefix_blocks=n_blocks)

    # -- disaggregated prefill/decode ---------------------------------
    def _kv_export_for(self, rid):
        """True when a dispatch to `rid` should arm KV handoff: the
        target is a PURE prefill replica and a decode-eligible replica
        is in rotation somewhere to receive the pages. "both" targets
        never export — they decode locally (today's behavior,
        handoff machinery stays cold)."""
        with self._lock:
            st = self._replicas.get(rid)
            if st is None or st.replica.role != "prefill":
                return False
            return any(o.state != "draining"
                       and o.replica.decode_eligible()
                       for r2, o in self._replicas.items() if r2 != rid)

    def _migrate(self, rr: RouterRequest):
        """Continue a handoff-terminal request on a decode replica.
        Target order: pure "decode" replicas by ascending load, then
        "both" replicas by ascending load, then the SOURCE replica
        itself — it just released the pages, so re-importing there is
        the never-dropped fallback (the request decodes locally, just
        without the disaggregation win). Returns (rid, ServingRequest);
        raises the last admission error only when every candidate
        including the source refused."""
        h = rr._sr.handoff
        src = rr.replica_id
        with self._lock:
            items = [(r, st.replica) for r, st in self._replicas.items()
                     if r != src and st.state != "draining"
                     and st.replica.decode_eligible()]
        # load() hops each replica's scheduler lock — outside ours
        ranked = sorted(((rep.role != "decode", rep.load(), r, rep)
                         for r, rep in items), key=lambda t: t[:2])
        cands = [(r, rep) for _, _, r, rep in ranked]
        src_rep = self.replica(src)
        if src_rep is not None:
            cands.append((src, src_rep))
        last_err = None
        for target, rep in cands:
            try:
                sr = rep.submit(rr._prompt, kv_import=h, **rr._params)
            except (BackpressureError, SchedulerClosedError) as e:
                last_err = e
                continue
            with self._lock:
                st = self._replicas.get(target)
                if st is not None:
                    st.dispatches += 1
            self.handoffs.inc()
            _flight.record("router.handoff", rid=str(sr.rid),
                           trace_id=sr.trace_id, from_replica=src,
                           to_replica=target, bytes=h.nbytes,
                           pages=h.pages)
            return target, sr
        raise last_err if last_err is not None else \
            SchedulerClosedError(
                f"router: no replica could continue handoff {rr.rid}")

    # -- failover ------------------------------------------------------
    def _redispatch(self, rr: RouterRequest):
        """Re-dispatch a failed-before-output request to a replica it
        has not tried. Returns (rid, ServingRequest) or None when no
        replica can take it."""
        tried = set(rr._tried)
        try:
            plan = self._plan(rr._key)
        except SchedulerClosedError:
            return None
        for target, _kind in plan:
            if target in tried:
                continue
            with self._lock:
                st = self._replicas.get(target)
            if st is None:
                continue
            try:
                sr = st.replica.submit(
                    rr._prompt, kv_export=self._kv_export_for(target),
                    **rr._params)
            except (BackpressureError, SchedulerClosedError):
                continue
            with self._lock:
                st.failovers_in += 1
            self.failovers.inc()
            _flight.record("router.failover", rid=str(sr.rid),
                           trace_id=sr.trace_id,
                           from_replica=rr.replica_id, to_replica=target,
                           attempt=rr.failovers + 1)
            return target, sr
        return None

    # -- health tracking ----------------------------------------------
    def _note_result(self, rid, state):
        """Terminal state of one dispatched request — drives the
        circuit breaker. Success closes, consecutive failures open,
        probe outcomes resolve half-open."""
        with self._lock:
            st = self._replicas.get(rid)
            if st is None:
                return
            if state in ("done", "handoff"):
                # a handoff is the prefill replica SUCCEEDING at its
                # half of the request — it closes breakers like "done"
                st.failures = 0
                if st.state in ("open", "half_open"):
                    st.state = "ok"
                    _flight.record("router.recovered", replica=rid)
            elif state == "failed":
                st.failures += 1
                if st.state == "half_open":
                    st.state = "open"        # failed probe: re-open
                    st.opened_at = time.monotonic()
                elif st.state == "ok" and \
                        st.failures >= self.unhealthy_after:
                    st.state = "open"
                    st.opened_at = time.monotonic()
                    self.unhealthy_transitions.inc()
                    _flight.record("router.unhealthy", replica=rid,
                                   failures=st.failures)
            # cancelled/expired say nothing about replica health

    # -- scheduler-surface duck type ----------------------------------
    def stats(self):
        with self._lock:
            items = [(rid, st.replica, st.state, st.failures,
                      st.dispatches, st.failovers_in)
                     for rid, st in self._replicas.items()]
        reps, queued, inflight, active, n_ready = {}, 0, 0, 0, 0
        n_closed = 0
        for rid, rep, state, failures, dispatches, fo in items:
            s = rep.stats()
            ready = state == "ok" and s["ready"]
            n_ready += ready
            n_closed += s.get("closed", False)
            queued += s["queued"]
            inflight += s["inflight"]
            active += s["active"]
            reps[rid] = {
                "health": state, "ready": ready,
                "role": s.get("role", "both"),
                "consecutive_failures": failures,
                "dispatches": dispatches, "failovers_in": fo,
                "queued": s["queued"], "inflight": s["inflight"],
                "active": s["active"], "requests": s.get("requests"),
            }
            # fleet mode: the worker's host tag rides every per-replica
            # payload; absent on in-process replicas (byte-identical)
            host = s.get("host") or getattr(rep, "host", None)
            if host is not None:
                reps[rid]["host"] = host
        self.ready_gauge.set(n_ready)
        # closed is LIVENESS (every pump gone), not readiness: a fully
        # paused pool is alive (healthz "ok") but not ready (readyz 503)
        return {"replicas": reps, "queued": queued,
                "inflight": inflight, "active": active,
                "replicas_ready": n_ready,
                "closed": n_closed == len(items),
                "router": {
                    "dispatches": self.dispatches.value,
                    "affinity_hits": self.affinity_hits.value,
                    "spills": self.spills.value,
                    "failovers": self.failovers.value,
                    "handoffs": self.handoffs.value,
                    "unhealthy_transitions":
                        self.unhealthy_transitions.value,
                }}

    def readiness(self):
        """Router readiness: at least one replica in rotation and
        accepting. Per-replica detail rides along so an external LB
        (or a human) sees who is out and why."""
        st = self.stats()
        detail = {rid: ("ok" if r["ready"] else r["health"])
                  for rid, r in st["replicas"].items()}
        return st["replicas_ready"] > 0, detail

    def pause(self):
        for rid in self.replica_ids:
            rep = self.replica(rid)
            if rep is not None:
                rep.pause()

    def resume(self):
        for rid in self.replica_ids:
            rep = self.replica(rid)
            if rep is not None:
                rep.resume()

    def drain(self, timeout=None):
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        ok = True
        for rid in self.replica_ids:
            rep = self.replica(rid)
            if rep is None:
                continue
            left = None if deadline is None \
                else max(deadline - time.monotonic(), 0.0)
            ok = rep.drain(timeout=left) and ok
        return ok

    def shutdown(self, drain=True, timeout=None):
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        ok = True
        for rid in self.replica_ids:
            rep = self.replica(rid)
            if rep is None:
                continue
            left = None if deadline is None \
                else max(deadline - time.monotonic(), 0.0)
            ok = rep.shutdown(drain=drain, timeout=left) and ok
        return ok

    # -- metrics aggregation ------------------------------------------
    def _scrape_gauge(self, rid, host=None):
        g = self._scrape_gauges.get(rid)
        if g is None:
            labels = {"replica": rid}
            if host is not None:
                labels["host"] = host
            g = self.registry.gauge(
                "pt_router_scrape_seconds",
                "Wall time of the last /metrics scrape of this "
                "replica's registry (a slow replica's exposition cost, "
                "made visible).", labels=labels)
            self._scrape_gauges[rid] = g
        return g

    @staticmethod
    def _scrape_replica(rep):
        """One replica's exposition. Goes through the scheduler when
        there is one so the scrape-side work that must never run on a
        pump (anomaly-sentinel analysis) happens here."""
        sched = getattr(rep, "scheduler", None)
        if sched is not None and hasattr(sched, "render_prometheus"):
            return sched.render_prometheus()
        return rep.registry.render_prometheus()

    def render_prometheus(self):
        """Router counters plus every replica's exposition with a
        `replica="<id>"` label injected on each series (HELP/TYPE
        comments are kept only for the router's own metrics — repeated
        per-replica TYPE lines would be invalid exposition).

        Lock discipline (TPL004, same as dispatch): the membership
        snapshot is taken under the router lock, but every replica
        scrape — registry render, relabel, sentinel scan — runs
        OUTSIDE it, so one replica's slow exposition can never stall
        submits. Each replica's scrape wall time lands in its
        `pt_router_scrape_seconds{replica=}` gauge."""
        self.stats()                 # refresh ready gauge
        with self._lock:
            items = [(rid, st.replica) for rid, st in
                     self._replicas.items()]
        parts = []
        for rid, rep in items:
            t0 = time.perf_counter()
            host = getattr(rep, "host", None)
            text = _relabel(self._scrape_replica(rep), rid, host=host)
            self._scrape_gauge(rid, host).set(time.perf_counter() - t0)
            parts.append(text)
        # the router's own registry renders LAST so the scrape gauges
        # it just set are current in the same exposition
        return "".join([self.registry.render_prometheus()] + parts)

    def metrics_snapshot(self):
        """JSON snapshot: router metrics flat (as the single-scheduler
        server exposes its registry) plus one nested snapshot per
        replica under "replicas"."""
        with self._lock:
            items = [(rid, st.replica) for rid, st in
                     self._replicas.items()]
        reps = {}
        for rid, rep in items:
            t0 = time.perf_counter()
            sched = getattr(rep, "scheduler", None)
            if sched is not None and hasattr(sched, "metrics_snapshot"):
                reps[rid] = sched.metrics_snapshot()
            else:
                reps[rid] = rep.registry.snapshot()
            host = getattr(rep, "host", None)
            if host is not None and isinstance(reps[rid], dict):
                reps[rid]["host"] = host
            self._scrape_gauge(rid, host).set(time.perf_counter() - t0)
        snap = self.registry.snapshot()
        snap["replicas"] = reps
        return snap

    def recent_requests(self, n=50):
        """Aggregate /debug/requests across the pool: each replica's
        recent terminal requests tagged with `replica=<id>`, merged in
        end-time order (newest last), trimmed to `n`. A migrated
        request appears once per replica that finalized it — the
        decode-side entry carries the full stitched timeline."""
        with self._lock:
            items = [(rid, st.replica) for rid, st in
                     self._replicas.items()]
        merged = []
        for rid, rep in items:
            sched = getattr(rep, "scheduler", None)
            if sched is None or not hasattr(sched, "recent_requests"):
                continue
            host = getattr(rep, "host", None)
            for entry in sched.recent_requests(n):
                e = dict(entry)
                e["replica"] = rid
                if host is not None:
                    e["host"] = host
                merged.append(e)
        # entries without a timeline sort stably at the front
        merged.sort(key=lambda e: (e.get("marks") or [[None, 0.0]])[-1][1])
        return merged[-int(n):] if n else merged

    def pulse(self, window=None, signals=None):
        """Aggregate /debug/pulse across the pool: one payload per
        replica under `replicas` (the `replica=` tag of the pulse
        plane), behind the same duck-typed method the single-scheduler
        server mounts. Same TPL004 discipline as the scrapes: the
        membership snapshot is taken under the router lock, every
        replica's (possibly sampling) pulse call runs OUTSIDE it."""
        with self._lock:
            items = [(rid, st.replica) for rid, st in
                     self._replicas.items()]
        reps = {}
        for rid, rep in items:
            sched = getattr(rep, "scheduler", None)
            if sched is not None and hasattr(sched, "pulse"):
                payload = sched.pulse(window=window, signals=signals)
                host = getattr(rep, "host", None)
                if host is not None and isinstance(payload, dict):
                    payload["host"] = host
                reps[rid] = payload
        return {"enabled": any(p.get("enabled") for p in reps.values()),
                "replicas": reps}

    # -- fleet observability (delegated to the attached plane) ---------
    def fleet_trace(self):
        """Merged, skew-corrected chrome trace across every fleet
        process — None when no FleetPlane is attached (the server maps
        that to 404)."""
        if self.fleet is None:
            return None
        return self.fleet.fleet_trace()

    def fleet_flightrecorder(self):
        """Merged flight-ring dump across the fleet — None when no
        FleetPlane is attached."""
        if self.fleet is None:
            return None
        return self.fleet.fleet_flightrecorder()


def _relabel(text, rid, host=None):
    """Inject replica="<rid>" — plus host="<host>" in fleet mode —
    into every series line of a Prometheus exposition (comment lines
    dropped — see render_prometheus)."""
    tag = f'replica="{rid}"'
    if host is not None:
        tag += f',host="{host}"'
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, rest = line.partition(" ")
        if "{" in name:
            base, _, labels = name.partition("{")
            name = f"{base}{{{tag},{labels}"
        else:
            name = f"{name}{{{tag}}}"
        out.append(f"{name} {rest}")
    return "\n".join(out) + "\n" if out else ""
